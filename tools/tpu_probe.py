"""Primitive-op probe on the live backend, with REAL synchronization.

Times the ops the grower redesign hinges on (sort, segmented cumsum,
scatter variants, row gather, while-step overhead, histogram kernels) and
banks results to JSON after every stage.  One process, one backend claim
(docs/PERFORMANCE.md single-tenant doctrine).

Run ALONE:  python tools/tpu_probe.py out.json
"""
import json
import os
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.utils.platform import _cache_dir  # noqa: E402

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())

OUT = sys.argv[1] if len(sys.argv) > 1 else os.path.join(REPO, "tpu_probe.json")
T0 = time.time()
DATA = {"started_utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
        "stages": []}


def bank(stage, **kw):
    kw["stage"] = stage
    kw["t_elapsed"] = round(time.time() - T0, 1)
    DATA["stages"].append(kw)
    tmp = OUT + ".tmp"
    # manual tmp+os.replace below; stdlib-only probe must stay
    # importable before jax/package init
    with open(tmp, "w") as f:  # tpulint: disable=atomic-write
        json.dump(DATA, f, indent=1, default=str)
    os.replace(tmp, OUT)
    print(f"[probe] {stage}: {json.dumps(kw, default=str)[:400]}", flush=True)


def main():
    t = time.time()
    try:
        import jax
        devs = jax.devices()
        import jax.numpy as jnp
        jnp.ones((8, 8)).sum().block_until_ready()
    except Exception as e:
        bank("init", error=str(e)[-600:])
        return 3
    import numpy as np
    d = devs[0]
    bank("init", seconds=round(time.time() - t, 1), platform=d.platform,
         kind=getattr(d, "device_kind", ""))
    if d.platform == "cpu" and os.environ.get("TM_ALLOW_CPU") != "1":
        bank("abort", reason="backend resolved to cpu")
        return 3

    from bench import dsync

    def timeit(name, fn, *args, reps=5):
        """Compile, then time reps with real sync; bank ms/call."""
        try:
            t0 = time.time()
            dsync(fn(*args))
            compile_s = time.time() - t0
            t0 = time.perf_counter()
            for _ in range(reps):
                dsync(fn(*args))
            ms = (time.perf_counter() - t0) / reps * 1e3
            bank(name, ms=round(ms, 3), compile_s=round(compile_s, 1))
            return ms
        except Exception as e:
            bank(name, error=str(e)[-400:],
                 tb=traceback.format_exc()[-600:])
            return None

    import jax
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.RandomState(0)

    # ---- sync overhead itself (floor for every timing here)
    one = jnp.ones((8,), jnp.float32)
    timeit("dsync_floor", jax.jit(lambda x: x + 1), one, reps=20)

    for n in (1_000_000, 5_000_000, 11_000_000):
        tag = f"{n//1_000_000}m"
        keys = jnp.asarray(rng.randint(0, 128, n).astype(np.int32))
        f32 = jnp.asarray(rng.rand(n).astype(np.float32))
        perm = jnp.asarray(rng.permutation(n).astype(np.int32))

        # sort: argsort of small-range i32 keys (segment-hist by sort)
        timeit(f"argsort_i32_{tag}", jax.jit(lambda k: jnp.argsort(k)), keys,
               reps=3)
        # sort with payload (lax.sort two operands, stable)
        timeit(f"sort_kv_{tag}",
               jax.jit(lambda k, v: lax.sort((k, v), is_stable=True,
                                             num_keys=1)[1]),
               keys, jnp.arange(n, dtype=jnp.int32), reps=3)
        # cumsum f32 (repartition building block)
        timeit(f"cumsum_f32_{tag}", jax.jit(lambda x: jnp.cumsum(x)), f32,
               reps=3)
        # cumsum i32
        timeit(f"cumsum_i32_{tag}", jax.jit(lambda x: jnp.cumsum(x)),
               keys, reps=3)
        # unique-scatter a permutation (inverse-permutation build)
        timeit(f"scatter_unique_perm_{tag}",
               jax.jit(lambda p: jnp.zeros(n, jnp.int32).at[p].set(
                   jnp.arange(n, dtype=jnp.int32), unique_indices=True,
                   mode="drop")), perm, reps=3)
        # scatter-add n updates into 128*64 bins (1-D, non-unique)
        timeit(f"scatter_add_flat_{tag}",
               jax.jit(lambda k, v: jnp.zeros(128 * 64, jnp.float32)
                       .at[k * 64].add(v)), keys, f32, reps=3)
        # segment_sum into 128 segments
        timeit(f"segment_sum128_{tag}",
               jax.jit(lambda k, v: jax.ops.segment_sum(
                   v, k, num_segments=128)), keys, f32, reps=3)
        del keys, f32, perm

    # ---- row gather: permute an [n, 28] u8 matrix (partition maintenance)
    for n in (1_000_000, 11_000_000):
        tag = f"{n//1_000_000}m"
        mat = jnp.asarray(rng.randint(0, 64, (n, 28)).astype(np.uint8))
        perm = jnp.asarray(rng.permutation(n).astype(np.int32))
        timeit(f"gather_rows_u8x28_{tag}",
               jax.jit(lambda m, p: jnp.take(m, p, axis=0)), mat, perm,
               reps=3)
        # scatter rows (inverse move): unique row scatter
        timeit(f"scatter_rows_u8x28_{tag}",
               jax.jit(lambda m, p: jnp.zeros_like(m).at[p].set(
                   m, unique_indices=True, mode="drop")), mat, perm, reps=3)
        # gather of one element per row (column pick, gl computation)
        col = jnp.asarray(rng.randint(0, 28, n).astype(np.int32))
        timeit(f"take_along_axis_{tag}",
               jax.jit(lambda m, c: jnp.take_along_axis(
                   m, c[:, None], axis=1)[:, 0]), mat, col, reps=3)
        del mat, perm, col

    # ---- histogram kernels, REAL sync, 1M and 8M rows
    from lightgbm_tpu.ops.histogram import build_histogram
    for n in (1_000_000, 8_000_000):
        tag = f"{n//1_000_000}m"
        binned = jnp.asarray(rng.randint(0, 63, (28, n)).astype(np.uint8))
        g = jnp.asarray(rng.randn(n).astype(np.float32))
        h = jnp.abs(g) + 0.1
        m = jnp.ones((n,), jnp.float32)
        for method in ("matmul", "pallas", "scatter"):
            timeit(f"hist_{method}_{tag}",
                   jax.jit(lambda b, gg, hh, mm, _m=method: build_histogram(
                       b, gg, hh, mm, 64, method=_m)), binned, g, h, m,
                   reps=3)
        del binned, g, h, m

    # ---- segment histogram (current scatter impl) at 1M x 28, 128 slots
    from lightgbm_tpu.ops.histogram import segment_histogram
    n = 1_000_000
    binned = jnp.asarray(rng.randint(0, 63, (28, n)).astype(np.uint8))
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    h = jnp.abs(g) + 0.1
    w = jnp.ones((n,), jnp.float32)
    slot = jnp.asarray(rng.randint(0, 129, n).astype(np.int32))
    timeit("seghist_scatter_1m",
           jax.jit(lambda b, gg, hh, ww, s: segment_histogram(
               b, gg, hh, ww, s, 128, 64)), binned, g, h, w, slot, reps=3)
    del binned, g, h, w, slot

    # ---- round-5b kernels: slot-expanded segment histogram, sorted
    # arena (new layout), router table matmul — at bench-relevant shapes
    from lightgbm_tpu.ops.histogram import (segment_histogram_expanded,
                                            segment_histogram_sorted,
                                            pack_cols_u32, take_from_table,
                                            capacity_schedule)
    for n in (1_000_000, 11_000_000):
        tag = f"{n//1_000_000}m"
        binned = jnp.asarray(rng.randint(0, 63, (28, n)).astype(np.uint8))
        g = jnp.asarray(rng.randn(n).astype(np.float32))
        h = jnp.abs(g) + 0.1
        w = jnp.ones((n,), jnp.float32)
        slot = jnp.asarray(rng.randint(0, 43, n).astype(np.int32))
        timeit(f"seghist_expanded42_{tag}",
               jax.jit(lambda b, gg, hh, ww, s: segment_histogram_expanded(
                   b, gg, hh, ww, s, 64, live_cap=42)),
               binned, g, h, w, slot, reps=3)
        slot128 = jnp.asarray(rng.randint(0, 129, n).astype(np.int32))
        caps = capacity_schedule(n)
        words, wb = pack_cols_u32(binned, g, h, w)
        # the pack rides as an ARGUMENT: production hoists it per tree, so
        # the probe must isolate the arena kernel, not per-call packing
        timeit(f"seghist_arena_t_{tag}",
               jax.jit(lambda b, gg, hh, ww, s, wd, _c=tuple(caps),
                       _w=wb: segment_histogram_sorted(
                           b, gg, hh, ww, s, 128, 64, caps=list(_c),
                           packed=(wd, _w))),
               binned, g, h, w, slot128, words, reps=3)
        leaf_id = jnp.asarray(rng.randint(0, 255, n).astype(np.int32))
        tbl = jnp.asarray(rng.randn(255, 9).astype(np.float32))
        timeit(f"table_matmul9_{tag}",
               jax.jit(lambda t, i: take_from_table(t, i, leading=True)),
               tbl, leaf_id, reps=3)
        tbl1 = jnp.asarray(rng.randn(255).astype(np.float32))
        timeit(f"table_matmul1_{tag}",
               jax.jit(take_from_table), tbl1, leaf_id, reps=3)
        del binned, g, h, w, slot, slot128, words, leaf_id
    del tbl, tbl1

    # ---- while_loop per-step overhead: tiny body, 1000 steps
    def loop_tiny(x):
        def body(c):
            i, v = c
            return i + 1, v * 1.000001 + 1e-9
        return lax.while_loop(lambda c: c[0] < 1000, body,
                              (jnp.int32(0), x))[1]
    timeit("while_1000_tiny_steps", jax.jit(loop_tiny),
           jnp.float32(1.0), reps=3)

    # medium body: ~64 elementwise ops on [255] vectors + a [255,28,64]
    # reduce per step, 100 steps (round-body overhead scale model)
    def loop_med(x):
        def body(c):
            i, v, hmat = c
            for _ in range(16):
                v = v * 1.0001 + jnp.roll(v, 1) * 1e-6
            s = hmat.sum(axis=(1, 2))
            return i + 1, v + s * 1e-9, hmat * 0.9999
        return lax.while_loop(lambda c: c[0] < 100, body,
                              (jnp.int32(0), x,
                               jnp.ones((255, 28, 64), jnp.float32)))[1]
    timeit("while_100_medium_steps", jax.jit(loop_med),
           jnp.ones((255,), jnp.float32), reps=3)

    # ---- dynamic_update_slice accumulator inside scan (block seg-hist)
    def scan_dus(parts, slots):
        def body(acc, xs):
            p, s = xs
            return lax.dynamic_update_slice(
                acc, (lax.dynamic_slice(acc, (s, 0), (1, 5376)) + p[None, :]),
                (s, 0)), None
        return lax.scan(body, jnp.zeros((129, 5376), jnp.float32),
                        (parts, slots))[0]
    nb = 2688
    parts = jnp.asarray(rng.rand(nb, 5376).astype(np.float32))
    slots = jnp.asarray(rng.randint(0, 128, nb).astype(np.int32))
    timeit("scan_dus_accum_2688blocks", jax.jit(scan_dus), parts, slots,
           reps=3)

    bank("done", total_seconds=round(time.time() - T0, 1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
