"""Decompose per-split cost of grow_tree on the live backend.

Times standalone jitted sub-ops at bench shapes, then whole grow_tree at
several leaf budgets to extract the per-iteration (per-split) cost.

Usage: python tools/profile_grow.py [n_rows] [max_bin]
"""
import functools
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.utils.platform import _cache_dir
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())

import jax
import jax.numpy as jnp

N = int(sys.argv[1]) if len(sys.argv) > 1 else 500_000
MAX_BIN = int(sys.argv[2]) if len(sys.argv) > 2 else 63
F = 28


def timeit(fn, *args, reps=5, warmup=1):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(fn(*args))
    return (time.perf_counter() - t0) / reps


def main():
    print("backend:", jax.default_backend(), jax.devices())
    rng = np.random.RandomState(0)
    X = rng.rand(N, F).astype(np.float32)
    w = rng.randn(F).astype(np.float32)
    y = ((X @ w) > 0).astype(np.float32)

    import lightgbm_tpu as lgb
    from lightgbm_tpu.ops import histogram as H
    from lightgbm_tpu.ops import split as S
    from lightgbm_tpu import grower as GR

    ds = lgb.Dataset(X, label=y, params={"max_bin": MAX_BIN})
    ds.construct()
    meta = ds.feature_meta()
    binned = jnp.asarray(np.ascontiguousarray(ds.binned.T))   # [G, n]
    G, n = binned.shape
    B = MAX_BIN + 1
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    hess = jnp.abs(grad) + 0.1
    mask = jnp.ones((n,), jnp.float32)
    member = jnp.asarray(rng.rand(n) < 0.25)

    print(f"n={n} G={G} B={B}")

    # -- sub-ops
    for method in ("matmul", "pallas", "scatter"):
        fn = jax.jit(functools.partial(H.build_histogram, num_bins=B,
                                       method=method))
        t = timeit(fn, binned, grad, hess, mask)
        print(f"hist[{method}] full-n: {t*1e3:.3f} ms")

    caps = H.capacity_schedule(n)
    print("caps:", caps)
    fn = jax.jit(functools.partial(H.compacted_histogram, num_bins=B,
                                   caps=caps, method="pallas"))
    t = timeit(fn, binned, grad, hess, mask, member)
    print(f"compacted hist (25% member): {t*1e3:.3f} ms")

    nz = jax.jit(lambda m: jnp.nonzero(m, size=caps[1], fill_value=n)[0])
    t = timeit(nz, member)
    print(f"nonzero(size={caps[1]}): {t*1e3:.3f} ms")

    hist = jax.jit(functools.partial(H.build_histogram, num_bins=B,
                                     method="pallas"))(
        binned, grad, hess, mask)
    m = meta.resolved()
    sg = jnp.sum(grad); sh = jnp.sum(hess); cnt = jnp.asarray(float(n))
    hp = S.SplitHyperparams()
    bs = jax.jit(lambda h: S.best_split_for_leaf(
        h, sg, sh, cnt, jnp.asarray(m.num_bin), jnp.asarray(m.missing_type),
        jnp.asarray(m.default_bin), jnp.asarray(m.is_categorical), hp))
    t = timeit(bs, hist)
    print(f"best_split_for_leaf: {t*1e3:.3f} ms")

    # partition update
    def part(leaf_id, thr):
        col = jnp.take(binned, 3, axis=0).astype(jnp.int32)
        gl = col <= thr
        in_leaf = leaf_id == 0
        return jnp.where(in_leaf & ~gl, 7, leaf_id)
    pj = jax.jit(part)
    t = timeit(pj, jnp.zeros(n, jnp.int32), jnp.asarray(30))
    print(f"partition update: {t*1e3:.3f} ms")

    # -- segment histogram (the rounds grower's hot op)
    from lightgbm_tpu.ops.histogram import compacted_segment_histogram
    L = 255
    slot = jnp.asarray(np.where(rng.rand(n) < 0.5,
                                rng.randint(0, 128, n), L).astype(np.int32))
    sh_fn = jax.jit(functools.partial(compacted_segment_histogram,
                                      num_slots=L, num_bins=B, caps=caps))
    t = timeit(sh_fn, binned, grad, hess, mask, slot)
    print(f"compacted segment hist (50% rows, 128 slots): {t*1e3:.3f} ms")

    # -- whole tree growth: rounds vs serial
    from lightgbm_tpu.grower import GrowerConfig, grow_tree
    from lightgbm_tpu.grower_rounds import grow_tree_rounds
    for name, fn_, leaves in (("rounds", grow_tree_rounds, 255),
                              ("rounds", grow_tree_rounds, 63),
                              ("serial", grow_tree, 255)):
        cfg = GrowerConfig(num_leaves=leaves, num_bins=B, hp=hp,
                           hist_method="pallas", compact=True)
        gt = jax.jit(functools.partial(fn_, meta=meta, cfg=cfg))
        t0 = time.perf_counter()
        out = gt(binned, grad, hess, mask)
        jax.block_until_ready(out)
        tc = time.perf_counter() - t0
        t = timeit(gt, binned, grad, hess, mask, reps=3, warmup=1)
        print(f"grow[{name}] leaves={leaves}: {t*1e3:.1f} ms "
              f"(compile {tc:.1f}s, num_leaves="
              f"{int(out[0].num_leaves)})", flush=True)

    print("done")


if __name__ == "__main__":
    main()
