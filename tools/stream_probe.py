#!/usr/bin/env python
"""Out-of-core streaming micro-bench: block pump throughput + overlap.

Measures, on the live backend, against a real spill store
(lightgbm_tpu/data/blockstore.py) built from synthetic rows:

- ``spill``: rows/sec of chunked binning + atomic block writes
  (``Dataset.from_sample(spill=...)``'s write path, run standalone);
- ``pump``: blocks/sec and GB/s of the double-buffered
  ``BlockPump`` (read + checksum-verify + ``jax.device_put`` + one
  touch op per block), next to the SAME scan with prefetch disabled —
  their ratio is ``overlap_efficiency`` (1.0 = the device_put of block
  t+1 fully hides behind block t's compute; <=1 observed when the
  reader can't keep ahead);
- host-RSS accounting: the planner's PREDICTED streamed host peak
  (``predict_host_peak_bytes``) next to the process's measured
  VmHWM delta across the scan — the number that says whether the
  host side of the two-level budget model is honest;
- the ``plan_stream`` verdict for the probed shape, journal-ready.

The LAST stdout line is a single JSON object so bench.py's worker can
bank it as a stage (``stage: stream_probe``;
``BENCH_SKIP_STREAM_PROBE=1`` skips the stage).

Usage:
    JAX_PLATFORMS=cpu python tools/stream_probe.py \
        [--rows 2000000] [--features 28] [--block-rows 262144] \
        [--passes 3]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_probe(rows: int = 2_000_000, features: int = 28,
              block_rows: int = 262_144, passes: int = 3) -> dict:
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.data.blockstore import BlockStore
    from lightgbm_tpu.data.stream import (BlockPump, host_rss_bytes,
                                          host_rss_peak_bytes)
    from lightgbm_tpu.ops.planner import (plan_stream,
                                          predict_host_peak_bytes)

    rows = int(rows)
    block_rows = min(int(block_rows), rows)
    out = {
        "rows": rows, "features": features, "block_rows": block_rows,
        "backend": jax.default_backend(),
        "plan": plan_stream(rows=rows, features=features,
                            num_bins=64).summary(),
    }

    path = tempfile.mkdtemp(prefix="stream_probe_")
    try:
        # -- spill: chunked binned-row writes (synthetic bins, so the
        # probe times the STORE, not the binning arithmetic)
        rng = np.random.RandomState(0)
        st = BlockStore.create(path, rows, features, np.uint8, block_rows)
        chunk = rng.randint(0, 64, (min(block_rows, rows), features),
                            dtype=np.uint8)
        t0 = time.perf_counter()
        done = 0
        while done < rows:
            take = min(chunk.shape[0], rows - done)
            st.append_rows(chunk[:take])
            done += take
        st.finalize()
        spill_s = time.perf_counter() - t0
        out["spill"] = {
            "seconds": round(spill_s, 3),
            "rows_per_sec": round(rows / max(spill_s, 1e-9), 1),
            "store_bytes": st.nbytes(),
            "num_blocks": st.num_blocks,
        }

        # -- pump: prefetch on vs off; one cheap device op per block so
        # the overlap has compute to hide behind
        touch = jax.jit(lambda b: jnp.sum(b.astype(jnp.int32)))

        def scan(prefetch: bool) -> float:
            best = float("inf")
            for _ in range(max(int(passes), 1)):
                t0 = time.perf_counter()
                acc = None
                for (_i, _s, _r, blk) in BlockPump(st, prefetch=prefetch):
                    acc = touch(blk) if acc is None else acc + touch(blk)
                acc.block_until_ready()
                best = min(best, time.perf_counter() - t0)
            return best

        rss_before_peak = host_rss_peak_bytes()
        rss_before = host_rss_bytes()
        warm = scan(prefetch=True)          # first scan pays checksums
        pumped = scan(prefetch=True)
        serial = scan(prefetch=False)
        gb = st.nbytes() / 1e9
        out["pump"] = {
            "first_scan_seconds": round(warm, 3),
            "seconds": round(pumped, 3),
            "seconds_no_prefetch": round(serial, 3),
            "blocks_per_sec": round(st.num_blocks / max(pumped, 1e-9), 1),
            "gb_per_sec": round(gb / max(pumped, 1e-9), 3),
            "overlap_efficiency": round(serial / max(pumped, 1e-9), 3),
        }
        pred_host = predict_host_peak_bytes(rows, features, 1,
                                            block_rows)[0]
        out["host_rss"] = {
            "predicted_stream_peak_bytes": int(pred_host),
            "measured_rss_bytes": host_rss_bytes(),
            "measured_rss_delta_bytes": host_rss_bytes() - rss_before,
            "measured_peak_bytes": host_rss_peak_bytes(),
            "measured_peak_delta_bytes":
                host_rss_peak_bytes() - rss_before_peak,
        }
    finally:
        shutil.rmtree(path, ignore_errors=True)
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=2_000_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--block-rows", type=int, default=262_144)
    ap.add_argument("--passes", type=int, default=3)
    a = ap.parse_args()
    out = run_probe(rows=a.rows, features=a.features,
                    block_rows=a.block_rows, passes=a.passes)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
