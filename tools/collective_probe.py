"""Collective micro-bench: per-tier psum payload bytes + reduction latency.

Measures one histogram reduction under each schedule the pod-scale plane
can elect (parallel/collectives.py) over a hybrid ("dcn", "ici") mesh —

- **flat**: one psum over both data axes (the XLA runtime schedules it);
- **hierarchical**: psum over the fast ICI tier, then the slow DCN tier;
- **voting**: ICI reduction of the full histogram, then only the top-k
  elected feature columns cross DCN (PV-Tree's bandwidth saver,
  grower.py ``leaf_best_voting``);

for the f32 AND quantized-integer payloads, next to the planner's
byte accounting (``ops.planner.plan_collectives`` — ici_bytes /
dcn_bytes per schedule).  Off-pod the latency numbers are virtual-mesh
relative figures; the BYTES are exact and are the acceptance signal:
voting's DCN bytes must sit strictly below data-parallel's at equal
trees on the same workload.

Usage: python tools/collective_probe.py [--rows N] [--features F]
       [--slices S] [--top-k K] [--reps R]
Prints one JSON object; bench.py wires this as the journaled
``collective_probe`` stage (BENCH_SKIP_COLLECTIVE_PROBE=1 skips).
"""

import argparse
import functools
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def run_probe(rows=200_000, features=28, max_bin=63, quant_bins=4,
              leaves=255, trees=100, num_slices=2, top_k=8,
              reps=5) -> dict:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from lightgbm_tpu.ops import histogram as H
    from lightgbm_tpu.ops.planner import plan_collectives
    from lightgbm_tpu.parallel.collectives import (DCN_AXIS, HYBRID_AXES,
                                                   ICI_AXIS)
    from lightgbm_tpu.parallel.learners import (make_hybrid_mesh,
                                                shard_map_compat)

    nd = jax.device_count()
    s = max(1, min(int(num_slices), nd))
    while nd % s != 0 and s > 1:
        s -= 1
    mesh = make_hybrid_mesh(nd - nd % s if s > 1 else nd, num_slices=s)
    d = int(mesh.shape[ICI_AXIS])
    B = max_bin + 1
    F = int(features)
    k = min(int(top_k), F)
    levels_per_tree = max(1.0, float(np.log2(max(leaves, 2))))
    rows_g = int(rows)

    rng = np.random.RandomState(0)
    hist_f = rng.randn(3, F, B).astype(np.float32)
    hist_i = rng.randint(-1000, 1000, (2, F, B)).astype(np.int32)

    def timed(fn, *args):
        r = fn(*args)
        jax.tree_util.tree_map(
            lambda x: np.asarray(x), r)                    # compile + sync
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.tree_util.tree_map(lambda x: np.asarray(x), fn(*args))
        return (time.perf_counter() - t0) / reps * 1e3

    def sched(body):
        return shard_map_compat(body, mesh=mesh, in_specs=(P(),),
                                out_specs=P(), check_vma=False)

    def flat(h):
        return lax.psum(h, HYBRID_AXES)

    def hier(h):
        return lax.psum(lax.psum(h, ICI_AXIS), DCN_AXIS)

    def vote(h):
        local = lax.psum(h, ICI_AXIS)
        # slice-level election stand-in: the top-k gain columns by |grad|
        score = jnp.abs(local[0]).sum(axis=-1)
        _, elected = lax.top_k(score, k)
        sub = lax.psum(local[:, elected], DCN_AXIS)
        return local.at[:, elected].set(sub)

    measured = {}
    for name, arr in (("f32", jnp.asarray(hist_f)),
                      ("quant", jnp.asarray(hist_i))):
        measured[name] = {
            "flat_ms": round(timed(jax.jit(sched(flat)), arr), 4),
            "hier_ms": round(timed(jax.jit(sched(hier)), arr), 4),
            "voting_ms": round(timed(jax.jit(sched(vote)), arr), 4),
        }

    # ---- planner byte accounting (the acceptance signal) ---------------
    out = {
        "rows": rows_g, "features": F, "max_bin": max_bin,
        "leaves": leaves, "trees": trees, "top_k": k,
        "mesh_shape": [s, d], "platform": jax.devices()[0].platform,
        "reps": reps, "measured_ms": measured,
    }
    for name, quant in (("f32", False), ("quant", True)):
        data = plan_collectives(
            features=F, num_bins=B, rows_global=rows_g, quant=quant,
            quant_bins=quant_bins, num_slices=s, devices_per_slice=d,
            voting_k=0)
        voting = plan_collectives(
            features=F, num_bins=B, rows_global=rows_g, quant=quant,
            quant_bins=quant_bins, num_slices=s, devices_per_slice=d,
            voting_k=k)
        reductions = levels_per_tree * trees
        out[name] = {
            "payload_bytes": data.payload_bytes,
            "data_parallel": dict(
                data.summary(),
                dcn_bytes_per_tree=int(data.dcn_bytes * levels_per_tree),
                dcn_bytes_total=int(data.dcn_bytes * reductions)),
            "voting_parallel": dict(
                voting.summary(),
                dcn_bytes_per_tree=int(voting.dcn_bytes * levels_per_tree),
                dcn_bytes_total=int(voting.dcn_bytes * reductions)),
            "voting_dcn_below_data": bool(
                s <= 1 or voting.dcn_bytes < data.dcn_bytes),
        }
    out["hierarchy_elected"] = bool(out["f32"]["data_parallel"]
                                    ["hierarchy_elected"])
    out["ici_bytes"] = int(out["f32"]["data_parallel"]["ici_bytes"])
    out["dcn_bytes"] = int(out["f32"]["data_parallel"]["dcn_bytes"])
    out["voting_k"] = k
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--max-bin", type=int, default=63)
    ap.add_argument("--quant-bins", type=int, default=4)
    ap.add_argument("--leaves", type=int, default=255)
    ap.add_argument("--trees", type=int, default=100)
    ap.add_argument("--slices", type=int, default=2)
    ap.add_argument("--top-k", type=int, default=8)
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args()
    out = run_probe(rows=args.rows, features=args.features,
                    max_bin=args.max_bin, quant_bins=args.quant_bins,
                    leaves=args.leaves, trees=args.trees,
                    num_slices=args.slices, top_k=args.top_k,
                    reps=args.reps)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
