#!/usr/bin/env python
"""Device-ingest micro-bench: the bucketize+pack kernel vs the host oracle.

The training and predict paths have hist_probe / predict_probe; this is
the ingest path's probe (ops/ingest.py).  It reports:

- **byte parity** on the full matrix of binning recipes — NaN routing,
  zero-as-bin EFB sparsity, categorical lookup, uint8 AND uint16 group
  dtypes — device bytes vs the host ``BinMapper.value_to_bin`` path on
  a salted block (zeros / all-NaN / +-1e30 / non-integer / negative
  codes).  Any mismatch raises: timings of wrong kernels are worthless;
- **measured utilization** per VMEM tile rung via
  ``obs/devprof.ingest_utilization_table`` (compiler-counted bytes +
  wall sec/call -> bin rows/sec, HBM GB/s) next to the wall-clocked
  host oracle at the same shape — the kernel-vs-host speedup is read
  straight off the table;
- **election**: what ``ops/planner.plan_ingest`` picks analytically,
  what it picks after the measured timings are banked into the
  autotune store's ``"i-..."`` family (cold vs warm, hit/miss/flip
  counters for bench_diff's election-quality gate);
- ``bin_rows_per_sec`` and ``kernel_speedup_vs_host`` — on accelerators
  at >= 1M rows the probe FAILS (raises) below 5x, the ISSUE 20
  acceptance bar; off-accelerator the kernel interprets (minutes per
  Mrow of jnp emulation), so rows are capped and only parity is
  enforced.

The LAST stdout line is a single JSON object so bench.py's worker can
bank it as a stage (``stage: ingest_probe``;
``BENCH_SKIP_INGEST_PROBE=1`` skips the stage).

Usage:
    JAX_PLATFORMS=cpu python tools/ingest_probe.py \
        [--rows 1000000] [--features 28] [--max-bin 63] [--reps 2]
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# off-accelerator the kernel runs in Pallas interpret mode — the
# timings mean nothing; cap the probe shape there
CPU_ROWS_CAP = 50_000


def _make_raw(rows, features, seed=0, categorical=True):
    """Synthetic block exercising every binning recipe at once: a
    categorical column, NaN routing, and two mostly-zero columns so EFB
    actually bundles (zero-as-bin + the fold's conflict semantics)."""
    rng = np.random.RandomState(seed)
    X = (rng.rand(rows, features) * 10.0).astype(np.float64)
    if categorical:
        X[:, 0] = rng.randint(0, 12, size=rows)
    X[rng.rand(rows) < 0.1, 2] = np.nan
    X[rng.rand(rows) < 0.7, 3] = 0.0
    if features > 5:
        X[rng.rand(rows) < 0.8, 5] = 0.0
    y = (rng.rand(rows) > 0.5).astype(np.float64)
    return X, y


def _build_dataset(rows, features, max_bin, categorical=True, seed=0):
    import lightgbm_tpu as lgb

    X, y = _make_raw(rows, features, seed=seed, categorical=categorical)
    params = {"objective": "binary", "verbosity": -1, "max_bin": max_bin}
    ds = lgb.Dataset(X, label=y, params=params,
                     categorical_feature=[0] if categorical else None)
    ds.construct()
    return ds, X


def parity_case(rows, features, max_bin, categorical, seed, label):
    """One cell of the parity matrix: device bytes vs the host oracle
    on a salted block, for one dataset recipe."""
    from lightgbm_tpu.ops import ingest as ING

    ds, X = _build_dataset(rows, features, max_bin, categorical, seed)
    tables = ING.build_ingest_tables(ds)
    binner = ING.DeviceBinner(tables)
    probe = np.concatenate([
        np.asarray(X[:512], np.float32),
        ING.salt_rows(features, np.asarray(X, np.float32))])
    ref = np.zeros((probe.shape[0], ds.num_groups), tables.out_dtype)
    with np.errstate(invalid="ignore"):      # host int64 cast of +-1e30
        ds._bin_block(probe.astype(np.float64), None, ref)
    got = np.asarray(binner(probe))
    return {"case": label, "rows": int(probe.shape[0]),
            "out_dtype": str(tables.out_dtype),
            "num_groups": int(ds.num_groups),
            "bit_equal": bool(np.array_equal(ref, got))}


def parity_matrix(features=12) -> dict:
    """NaN / zero-as-bin / categorical / uint8+uint16: the acceptance
    criterion's full matrix (max_bin=1000 forces a >256-bin group, the
    uint16 arm)."""
    cases = [
        parity_case(2000, features, 63, True, 0, "uint8+cat+nan+zero"),
        parity_case(2000, features, 1000, True, 1, "uint16+cat+nan+zero"),
        parity_case(2000, features, 63, False, 2, "uint8 numerical"),
    ]
    return {"cases": cases, "ok": all(c["bit_equal"] for c in cases)}


def autotune_probe(rows, features, num_groups, item_bytes,
                   kernel_sec, host_sec) -> dict:
    """Bank the measured kernel/host timings into the planner's
    ``"i-..."`` autotune family and run the election cold and warm —
    the ingest twin of predict_probe's autotune column."""
    from lightgbm_tpu.ops import planner as P

    out = {"enabled": P.autotune_enabled(), "store_dir": P.autotune_dir()}
    if not (P.autotune_enabled() and P.autotune_dir()):
        out["skipped"] = ("no autotune store configured: set "
                          "LGBM_TPU_AUTOTUNE_DIR or LGBM_TPU_COMPILE_CACHE")
        return out
    P.autotune_counters(reset=True)

    def plan():
        return P.plan_ingest(rows=rows, features=features,
                             num_groups=num_groups, item_bytes=item_bytes)

    cold = plan()
    P.record_ingest_timing(rows, features, num_groups, item_bytes,
                           "kernel", kernel_sec)
    P.record_ingest_timing(rows, features, num_groups, item_bytes,
                           "host", host_sec)
    warm = plan()
    counters = P.autotune_counters()
    out.update({
        "shape_bucket": warm.autotune_key,
        "cold_variant": cold.variant,
        "cold_elected_by": cold.elected_by,
        "warm_variant": warm.variant,
        "warm_elected_by": warm.elected_by,
        "winner": "kernel" if kernel_sec < host_sec else "host",
        "seconds_per_call": {"kernel": kernel_sec, "host": host_sec},
        "autotune_hits": counters["hits"],
        "autotune_misses": counters["misses"],
        "autotune_flips": counters["flips"],
    })
    return out


def run_probe(rows=1_000_000, features=28, max_bin=63, reps=2) -> dict:
    import jax

    from lightgbm_tpu.obs.devprof import ingest_utilization_table
    from lightgbm_tpu.ops import planner as P
    from lightgbm_tpu.ops.histogram import on_accelerator

    accel = on_accelerator()
    if not accel:
        rows = min(int(rows), CPU_ROWS_CAP)
    out = {"rows": int(rows), "features": int(features),
           "max_bin": int(max_bin),
           "platform": jax.devices()[0].platform, "accelerator": accel}

    # ---- parity first: timings of wrong kernels are worthless ---------
    out["parity"] = parity_matrix(features=min(int(features), 12))
    if not out["parity"]["ok"]:
        raise RuntimeError(f"ingest parity FAILED: {out['parity']}")

    # ---- measured utilization at the bench workload's shape -----------
    # numerical-only data: the synthetic-HIGGS matrix the bin_seconds
    # acceptance bar is stated against
    ds, X = _build_dataset(int(rows), int(features), int(max_bin),
                           categorical=False, seed=3)
    table = ingest_utilization_table(ds, np.asarray(X, np.float32),
                                     reps=reps)
    out["utilization"] = table
    speedup = table.get("kernel_speedup_vs_host")
    if speedup is not None:
        out["kernel_speedup_vs_host"] = speedup
        out["bin_rows_per_sec"] = table.get("bin_rows_per_sec")
        if accel and rows >= 1_000_000 and speedup < 5.0:
            raise RuntimeError(
                f"ingest kernel is only {speedup}x faster than the host "
                f"oracle at {rows} rows — below the 5x acceptance bar")

    # ---- election: the plan this shape would train under --------------
    item = np.dtype(table["out_dtype"]).itemsize
    out["plan"] = P.plan_ingest(
        rows=int(rows), features=int(features),
        num_groups=int(table["num_groups"]), item_bytes=item).summary()

    # ---- autotune family: banked timings steer the next election ------
    kernel_sec = table.get("best_kernel_seconds_per_call")
    host_sec = table.get("host", {}).get("seconds_per_call")
    if kernel_sec and host_sec:
        out["autotune"] = autotune_probe(
            int(rows), int(features), int(table["num_groups"]), item,
            kernel_sec, host_sec)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--max-bin", type=int, default=63)
    ap.add_argument("--reps", type=int, default=2)
    args = ap.parse_args()
    out = run_probe(args.rows, args.features, args.max_bin, args.reps)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
