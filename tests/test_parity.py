"""Numerical parity vs the locally built reference implementation.

SURVEY.md section 4 prescribes a parity harness the reference itself lacks:
train the same data through this package and through stock LightGBM
(built from /root/reference by tools/build_reference.sh, staged at
/tmp/refpkg) and compare metric trajectories and model-text cross-loading.

Skipped wholesale when the reference lib is absent (CI/bench images build it
once; ~2 min).  The reference package is pure ctypes so importing it next to
the JAX stack is safe.

Measured facts these tests pin down (round 3, binary.train 7000x28):

==========  =========================  =========================
config      reference AUC              this repo AUC
==========  =========================  =========================
30r plain   0.8825759152573261         0.8809875801255787
30r bag .7  0.882125915650661          0.8816582569498983
20r weight  0.8575449931338933         0.8574...
iter-1 AUC  0.768800830329785          0.7688008303297851
==========  =========================  =========================

i.e. the round-1/2 "accuracy plateau" was the dataset at 30 rounds, not a
split-quality deficiency: the reference plateaus identically (and reaches
0.975 only at 100 rounds).  Bonus root cause: in this reference checkout
``boosting=goss`` never samples at all -- GOSS::Bagging delegates to
GBDT::Bagging (src/boosting/goss.hpp:129) whose guard requires
``bag_data_cnt_ < num_data_`` (src/boosting/gbdt.cpp:214), but with GOSS's
mandatory bagging_freq=0 ResetBaggingConfig leaves bag_data_cnt_ == num_data_
forever, so reference GOSS == reference GBDT bit-for-bit.  This repo
implements the *intended* GOSS (top-rate keep + other-rate sample after the
1/learning_rate warm-up), which is why its GOSS trajectory legitimately
differs from plain.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REFPKG = os.environ.get("LGBM_REF_PKG", "/tmp/refpkg")
EXAMPLES = "/root/reference/examples"

pytestmark = pytest.mark.skipif(
    not os.path.exists(os.path.join(REFPKG, "lightgbm", "lib_lightgbm.so")),
    reason="reference lib not built (run tools/build_reference.sh)",
)


@pytest.fixture(scope="module")
def reflgb():
    sys.path.insert(0, REFPKG)
    import lightgbm
    return lightgbm


@pytest.fixture(scope="module")
def binary_train():
    d = np.loadtxt(f"{EXAMPLES}/binary_classification/binary.train")
    return d[:, 1:], d[:, 0]


@pytest.fixture(scope="module")
def binary_test():
    d = np.loadtxt(f"{EXAMPLES}/binary_classification/binary.test")
    return d[:, 1:], d[:, 0]


def _train_auc_traj(pkg, X, y, params, nbr):
    ev = {}
    tr = pkg.Dataset(X, label=y)
    bst = pkg.train(params, tr, num_boost_round=nbr,
                    valid_sets=[pkg.Dataset(X, label=y, reference=tr)],
                    evals_result=ev, verbose_eval=False)
    return bst, ev["valid_0"]["auc"]


BASE = {"objective": "binary", "metric": "auc", "verbosity": -1}


def test_auc_trajectory_parity(reflgb, binary_train):
    import lightgbm_tpu as lgb
    X, y = binary_train
    _, ours = _train_auc_traj(lgb, X, y, dict(BASE), 30)
    _, ref = _train_auc_traj(reflgb, X, y, dict(BASE), 30)
    # iteration 1 must agree to float precision: same binning, same root
    # histogram, same first split set (reference value 0.768800830329785)
    assert abs(ours[0] - ref[0]) < 1e-9
    # accumulated tie-breaking/fp drift stays small across 30 rounds
    diffs = np.abs(np.asarray(ours) - np.asarray(ref))
    assert diffs.max() < 5e-3, f"trajectory diverged: max {diffs.max():.4g}"
    assert abs(ours[-1] - ref[-1]) < 3e-3


def test_model_cross_load_ours_to_ref(reflgb, binary_train, binary_test,
                                      tmp_path):
    """A model saved by this package parses in the reference C++ loader
    (gbdt_model_text.cpp:405) with identical predictions."""
    import lightgbm_tpu as lgb
    X, y = binary_train
    Xt, _ = binary_test
    bst = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 15},
                    lgb.Dataset(X, label=y), num_boost_round=10,
                    verbose_eval=False)
    path = str(tmp_path / "ours.txt")
    bst.save_model(path)
    ref_pred = reflgb.Booster(model_file=path).predict(Xt)
    np.testing.assert_allclose(bst.predict(Xt), ref_pred, atol=1e-12)


def test_model_cross_load_ref_to_ours(reflgb, binary_train, binary_test,
                                      tmp_path):
    import lightgbm_tpu as lgb
    X, y = binary_train
    Xt, _ = binary_test
    ref_bst = reflgb.train(
        {"objective": "binary", "verbosity": -1, "num_leaves": 15},
        reflgb.Dataset(X, label=y), num_boost_round=10)
    path = str(tmp_path / "ref.txt")
    ref_bst.save_model(path)
    ours = lgb.Booster(model_file=path)
    np.testing.assert_allclose(ours.predict(Xt), ref_bst.predict(Xt),
                               atol=1e-12)


def test_multiclass_parity(reflgb):
    import lightgbm_tpu as lgb
    d = np.loadtxt(f"{EXAMPLES}/multiclass_classification/multiclass.train")
    X, y = d[:, 1:], d[:, 0]
    params = {"objective": "multiclass", "num_class": 5,
              "metric": "multi_logloss", "verbosity": -1}

    def run(pkg):
        ev = {}
        tr = pkg.Dataset(X, label=y)
        pkg.train(params, tr, num_boost_round=20,
                  valid_sets=[pkg.Dataset(X, label=y, reference=tr)],
                  evals_result=ev, verbose_eval=False)
        return ev["valid_0"]["multi_logloss"]

    ours, ref = run(lgb), run(reflgb)
    assert abs(ours[0] - ref[0]) < 1e-6
    assert abs(ours[-1] - ref[-1]) < 2e-2


def test_regression_parity(reflgb):
    import lightgbm_tpu as lgb
    d = np.loadtxt(f"{EXAMPLES}/regression/regression.train")
    X, y = d[:, 1:], d[:, 0]
    params = {"objective": "regression", "metric": "l2", "verbosity": -1}

    def run(pkg):
        ev = {}
        tr = pkg.Dataset(X, label=y)
        pkg.train(params, tr, num_boost_round=20,
                  valid_sets=[pkg.Dataset(X, label=y, reference=tr)],
                  evals_result=ev, verbose_eval=False)
        return ev["valid_0"]["l2"]

    ours, ref = run(lgb), run(reflgb)
    assert abs(ours[0] - ref[0]) < 1e-7
    assert abs(ours[-1] - ref[-1]) < 2e-3
