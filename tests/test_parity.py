"""Numerical parity vs the locally built reference implementation.

SURVEY.md section 4 prescribes a parity harness the reference itself lacks:
train the same data through this package and through stock LightGBM
(built from /root/reference by tools/build_reference.sh, staged at
/tmp/refpkg) and compare metric trajectories and model-text cross-loading.

Skipped wholesale when the reference lib is absent (CI/bench images build it
once; ~2 min).  The reference package is pure ctypes so importing it next to
the JAX stack is safe.

Measured facts these tests pin down (round 3, binary.train 7000x28):

==========  =========================  =========================
config      reference AUC              this repo AUC
==========  =========================  =========================
30r plain   0.8825759152573261         0.8809875801255787
30r bag .7  0.882125915650661          0.8816582569498983
20r weight  0.8575449931338933         0.8574...
iter-1 AUC  0.768800830329785          0.7688008303297851
==========  =========================  =========================

i.e. the round-1/2 "accuracy plateau" was the dataset at 30 rounds, not a
split-quality deficiency: the reference plateaus identically (and reaches
0.975 only at 100 rounds).  Bonus root cause: in this reference checkout
``boosting=goss`` never samples at all -- GOSS::Bagging delegates to
GBDT::Bagging (src/boosting/goss.hpp:129) whose guard requires
``bag_data_cnt_ < num_data_`` (src/boosting/gbdt.cpp:214), but with GOSS's
mandatory bagging_freq=0 ResetBaggingConfig leaves bag_data_cnt_ == num_data_
forever, so reference GOSS == reference GBDT bit-for-bit.  This repo
implements the *intended* GOSS (top-rate keep + other-rate sample after the
1/learning_rate warm-up), which is why its GOSS trajectory legitimately
differs from plain.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REFPKG = os.environ.get("LGBM_REF_PKG", "/tmp/refpkg")
EXAMPLES = "/root/reference/examples"
_REFLIB = os.path.join(REFPKG, "lightgbm", "lib_lightgbm.so")


def _ensure_reference_built() -> str:
    """Build the reference lib on demand (~2 min, cached in /tmp across
    runs) so the parity suite executes unskipped on any image with the
    toolchain; set LGBM_REF_SKIP_BUILD=1 to skip instead.  Called from the
    reflgb fixture (NOT at import time: collection must stay cheap) and
    serialized through a lock file for parallel pytest workers."""
    if os.path.exists(_REFLIB):
        return ""
    if os.environ.get("LGBM_REF_SKIP_BUILD") == "1":
        return "reference lib not built (LGBM_REF_SKIP_BUILD=1)"
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "build_reference.sh")
    import fcntl
    with open("/tmp/lgb_refbuild.lock", "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)   # one builder at a time
        if os.path.exists(_REFLIB):         # another worker built it
            return ""
        proc = subprocess.Popen(["sh", script], stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
        try:
            out, _ = proc.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            import signal
            os.killpg(proc.pid, signal.SIGKILL)   # sh AND make children
            proc.wait()
            return "reference build timed out"
        if proc.returncode != 0:
            return f"reference build failed rc={proc.returncode}: " \
                   f"{out.decode()[-300:]}"
    return "" if os.path.exists(_REFLIB) else "reference build produced no lib"


@pytest.fixture(scope="module")
def reflgb():
    reason = _ensure_reference_built()
    if reason:
        pytest.skip(reason)
    sys.path.insert(0, REFPKG)
    import lightgbm
    return lightgbm


@pytest.fixture(scope="module")
def binary_train():
    d = np.loadtxt(f"{EXAMPLES}/binary_classification/binary.train")
    return d[:, 1:], d[:, 0]


@pytest.fixture(scope="module")
def binary_test():
    d = np.loadtxt(f"{EXAMPLES}/binary_classification/binary.test")
    return d[:, 1:], d[:, 0]


def _train_auc_traj(pkg, X, y, params, nbr):
    ev = {}
    tr = pkg.Dataset(X, label=y)
    bst = pkg.train(params, tr, num_boost_round=nbr,
                    valid_sets=[pkg.Dataset(X, label=y, reference=tr)],
                    evals_result=ev, verbose_eval=False)
    return bst, ev["valid_0"]["auc"]


BASE = {"objective": "binary", "metric": "auc", "verbosity": -1}


def test_auc_trajectory_parity(reflgb, binary_train):
    import lightgbm_tpu as lgb
    X, y = binary_train
    _, ours = _train_auc_traj(lgb, X, y, dict(BASE), 30)
    _, ref = _train_auc_traj(reflgb, X, y, dict(BASE), 30)
    # iteration 1 must agree to float precision: same binning, same root
    # histogram, same first split set (reference value 0.768800830329785)
    assert abs(ours[0] - ref[0]) < 1e-9
    # accumulated tie-breaking/fp drift stays small across 30 rounds
    diffs = np.abs(np.asarray(ours) - np.asarray(ref))
    assert diffs.max() < 5e-3, f"trajectory diverged: max {diffs.max():.4g}"
    assert abs(ours[-1] - ref[-1]) < 3e-3


def test_model_cross_load_ours_to_ref(reflgb, binary_train, binary_test,
                                      tmp_path):
    """A model saved by this package parses in the reference C++ loader
    (gbdt_model_text.cpp:405) with identical predictions."""
    import lightgbm_tpu as lgb
    X, y = binary_train
    Xt, _ = binary_test
    bst = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 15},
                    lgb.Dataset(X, label=y), num_boost_round=10,
                    verbose_eval=False)
    path = str(tmp_path / "ours.txt")
    bst.save_model(path)
    ref_pred = reflgb.Booster(model_file=path).predict(Xt)
    np.testing.assert_allclose(bst.predict(Xt), ref_pred, atol=1e-12)


def test_model_cross_load_ref_to_ours(reflgb, binary_train, binary_test,
                                      tmp_path):
    import lightgbm_tpu as lgb
    X, y = binary_train
    Xt, _ = binary_test
    ref_bst = reflgb.train(
        {"objective": "binary", "verbosity": -1, "num_leaves": 15},
        reflgb.Dataset(X, label=y), num_boost_round=10)
    path = str(tmp_path / "ref.txt")
    ref_bst.save_model(path)
    ours = lgb.Booster(model_file=path)
    np.testing.assert_allclose(ours.predict(Xt), ref_bst.predict(Xt),
                               atol=1e-12)


def test_multiclass_parity(reflgb):
    import lightgbm_tpu as lgb
    d = np.loadtxt(f"{EXAMPLES}/multiclass_classification/multiclass.train")
    X, y = d[:, 1:], d[:, 0]
    params = {"objective": "multiclass", "num_class": 5,
              "metric": "multi_logloss", "verbosity": -1}

    def run(pkg):
        ev = {}
        tr = pkg.Dataset(X, label=y)
        pkg.train(params, tr, num_boost_round=20,
                  valid_sets=[pkg.Dataset(X, label=y, reference=tr)],
                  evals_result=ev, verbose_eval=False)
        return ev["valid_0"]["multi_logloss"]

    ours, ref = run(lgb), run(reflgb)
    assert abs(ours[0] - ref[0]) < 1e-6
    assert abs(ours[-1] - ref[-1]) < 2e-2


def test_regression_parity(reflgb):
    import lightgbm_tpu as lgb
    d = np.loadtxt(f"{EXAMPLES}/regression/regression.train")
    X, y = d[:, 1:], d[:, 0]
    params = {"objective": "regression", "metric": "l2", "verbosity": -1}

    def run(pkg):
        ev = {}
        tr = pkg.Dataset(X, label=y)
        pkg.train(params, tr, num_boost_round=20,
                  valid_sets=[pkg.Dataset(X, label=y, reference=tr)],
                  evals_result=ev, verbose_eval=False)
        return ev["valid_0"]["l2"]

    ours, ref = run(lgb), run(reflgb)
    assert abs(ours[0] - ref[0]) < 1e-7
    assert abs(ours[-1] - ref[-1]) < 2e-3


def _load_svm(path):
    """rank.train is LibSVM-format; densify via the reference loader-free
    parser (small files)."""
    labels, rows, maxf = [], [], 0
    with open(path) as f:
        for line in f:
            parts = line.split()
            labels.append(float(parts[0]))
            d = {}
            for tok in parts[1:]:
                k, v = tok.split(":")
                d[int(k)] = float(v)
                maxf = max(maxf, int(k))
            rows.append(d)
    X = np.zeros((len(rows), maxf + 1))
    for i, d in enumerate(rows):
        for k, v in d.items():
            X[i, k] = v
    return X, np.asarray(labels)


def test_lambdarank_trajectory_parity(reflgb):
    """NDCG trajectory parity on the stock lambdarank example (reference:
    rank_objective.hpp LambdarankNDCG; DCGCalculator label gains)."""
    import lightgbm_tpu as lgb
    X, y = _load_svm(f"{EXAMPLES}/lambdarank/rank.train")
    group = np.loadtxt(f"{EXAMPLES}/lambdarank/rank.train.query").astype(int)
    params = {"objective": "lambdarank", "metric": "ndcg",
              "ndcg_eval_at": [5], "verbosity": -1, "num_leaves": 31,
              "min_data_in_leaf": 20}

    def run(pkg):
        ev = {}
        tr = pkg.Dataset(X, label=y, group=group)
        bst = pkg.train(params, tr, num_boost_round=20,
                        valid_sets=[pkg.Dataset(X, label=y, group=group,
                                                reference=tr)],
                        evals_result=ev, verbose_eval=False)
        return bst, ev["valid_0"]["ndcg@5"]

    (bo, ours), (br, ref) = run(lgb), run(reflgb)
    # iteration 1 agrees to ~1e-3, not exactly: this package computes exact
    # sigmoids where the reference quantizes through a lookup table
    # (rank_objective.hpp:234-255; deviation documented in
    # objective_rank.py), so lambdas — and the first tree — differ in the
    # table's quantization error.  Measured round 4: |diff| = 3.2e-4.
    assert abs(ours[0] - ref[0]) < 1e-3, (ours[0], ref[0])
    assert abs(ours[-1] - ref[-1]) < 1e-2, (ours[-1], ref[-1])


def test_lambdarank_model_cross_load(reflgb, tmp_path):
    import lightgbm_tpu as lgb
    X, y = _load_svm(f"{EXAMPLES}/lambdarank/rank.train")
    group = np.loadtxt(f"{EXAMPLES}/lambdarank/rank.train.query").astype(int)
    Xt, _ = _load_svm(f"{EXAMPLES}/lambdarank/rank.test")
    Xt = Xt[:, :X.shape[1]] if Xt.shape[1] >= X.shape[1] else np.pad(
        Xt, ((0, 0), (0, X.shape[1] - Xt.shape[1])))
    bst = lgb.train({"objective": "lambdarank", "verbosity": -1,
                     "num_leaves": 15},
                    lgb.Dataset(X, label=y, group=group), num_boost_round=8)
    path = str(tmp_path / "rank.txt")
    bst.save_model(path)
    np.testing.assert_allclose(
        bst.predict(Xt), reflgb.Booster(model_file=path).predict(Xt),
        atol=1e-12)


def test_multiclass_model_cross_load(reflgb, tmp_path):
    import lightgbm_tpu as lgb
    d = np.loadtxt(f"{EXAMPLES}/multiclass_classification/multiclass.train")
    X, y = d[:, 1:], d[:, 0]
    bst = lgb.train({"objective": "multiclass", "num_class": 5,
                     "verbosity": -1, "num_leaves": 15},
                    lgb.Dataset(X, label=y), num_boost_round=6)
    path = str(tmp_path / "mc.txt")
    bst.save_model(path)
    np.testing.assert_allclose(
        bst.predict(X[:500]),
        reflgb.Booster(model_file=path).predict(X[:500]), atol=1e-12)


def _categorical_xy(n=5000, seed=5):
    rng = np.random.RandomState(seed)
    c1 = rng.randint(0, 12, n).astype(np.float64)
    c2 = rng.randint(0, 40, n).astype(np.float64)
    x3 = rng.rand(n)
    logit = (np.isin(c1, [2, 3, 7]) * 1.4 + (c2 % 5 == 0) * 0.9
             + 1.2 * x3 - 1.2 + 0.3 * rng.randn(n))
    y = (logit > 0).astype(np.float64)
    return np.column_stack([c1, c2, x3]), y


def test_categorical_trajectory_parity(reflgb):
    """Categorical split parity: count-sorted bins, one-hot and sorted
    many-vs-many categorical thresholds (reference:
    FindBestThresholdCategoricalInner, feature_histogram.hpp:259)."""
    import lightgbm_tpu as lgb
    X, y = _categorical_xy()
    params = {"objective": "binary", "metric": "auc", "verbosity": -1,
              "num_leaves": 15, "min_data_in_leaf": 20,
              "categorical_feature": [0, 1]}

    def run(pkg):
        ev = {}
        tr = pkg.Dataset(X, label=y, categorical_feature=[0, 1])
        pkg.train(params, tr, num_boost_round=20,
                  valid_sets=[pkg.Dataset(X, label=y, reference=tr,
                                          categorical_feature=[0, 1])],
                  evals_result=ev, verbose_eval=False)
        return ev["valid_0"]["auc"]

    ours, ref = run(lgb), run(reflgb)
    # iteration 1 agrees to ~5e-4, not exactly: categorical candidate
    # pruning here uses EXACT per-bin counts where the reference estimates
    # counts as RoundInt(hess * cnt_factor) (feature_histogram.hpp:813;
    # deviation documented in ops/split.py), shifting which categories
    # clear min_data_per_group.  Measured round 4: |diff| = 1.5e-4.
    assert abs(ours[0] - ref[0]) < 5e-4, (ours[0], ref[0])
    assert abs(ours[-1] - ref[-1]) < 5e-3, (ours[-1], ref[-1])


def test_categorical_model_cross_load(reflgb, tmp_path):
    import lightgbm_tpu as lgb
    X, y = _categorical_xy()
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 15, "categorical_feature": [0, 1]},
                    lgb.Dataset(X, label=y, categorical_feature=[0, 1]),
                    num_boost_round=8)
    path = str(tmp_path / "cat.txt")
    bst.save_model(path)
    np.testing.assert_allclose(
        bst.predict(X[:500]),
        reflgb.Booster(model_file=path).predict(X[:500]), atol=1e-12)


def test_large_scale_parity_150k(reflgb):
    """Trajectory parity at >=100k rows (VERDICT round-3 item 9: previous
    parity evidence topped out at 7k rows)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    n = 150_000
    X = rng.rand(n, 12).astype(np.float64)
    w = rng.randn(12)
    logit = X @ w + 1.5 * X[:, 0] * X[:, 1] + 0.5 * rng.randn(n)
    y = (logit > np.median(logit)).astype(np.float64)
    params = {"objective": "binary", "metric": "auc", "verbosity": -1,
              "num_leaves": 63, "min_data_in_leaf": 20, "max_bin": 63}

    def run(pkg):
        ev = {}
        tr = pkg.Dataset(X, label=y)
        pkg.train(params, tr, num_boost_round=10,
                  valid_sets=[pkg.Dataset(X, label=y, reference=tr)],
                  evals_result=ev, verbose_eval=False)
        return ev["valid_0"]["auc"]

    ours, ref = run(lgb), run(reflgb)
    assert abs(ours[0] - ref[0]) < 1e-7, (ours[0], ref[0])
    diffs = np.abs(np.asarray(ours) - np.asarray(ref))
    assert diffs.max() < 3e-3, f"diverged: {diffs.max():.4g}"
