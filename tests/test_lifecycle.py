"""Guarded model lifecycle (lightgbm_tpu/lifecycle/): continual
refresh, shadow/canary promotion, automated rollback, crash-resume,
freshness SLO (docs/LIFECYCLE.md).

The load-bearing claims:

* a clean promotion serves the candidate bit-identically and resets
  ``model_age_seconds``;
* EVERY gate breach — drift, latency, error rate, non-finite outputs,
  a corrupt bundle, a crash — leaves the fleet serving the previous
  model BYTE-identically and dumps a flight bundle naming the gate;
* a restarted pipeline resumes a committed cutover or rolls back —
  never double-promotes;
* fresh rows are binned on the deployed model's frozen bin grid, so
  a streamed (chunked) refresh is byte-identical to a resident one.

All CPU-runnable under the tier-1 command; chaos faults ride the PR 2
``ChaosRegistry`` (``serving`` site) and ``chaos://`` filesystem.
"""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.engine import InitModelCompatibilityError
from lightgbm_tpu.lifecycle import (CANARY_SUFFIX, LifecycleConfig,
                                    LifecycleController, booster_digest,
                                    fresh_dataset, replay_traffic)
from lightgbm_tpu.lifecycle.journal import (RolloutJournal,
                                            RolloutJournalError)
from lightgbm_tpu.obs.flight import FlightRecorder, global_flight
from lightgbm_tpu.obs.metrics import MetricsRegistry
from lightgbm_tpu.obs.watchdog import SLOConfig, Watchdog, global_watchdog
from lightgbm_tpu.resilience.checkpoint import (CheckpointManager,
                                                CheckpointNotFoundError)
from lightgbm_tpu.resilience.faults import ChaosRegistry

pytestmark = pytest.mark.lifecycle

F = 8


@pytest.fixture(autouse=True)
def _flight_tmp(tmp_path, monkeypatch):
    """Every test gets its own flight-bundle dir and a fresh dump
    budget (rollbacks dump on purpose; the per-process cap must not
    starve later tests)."""
    monkeypatch.setattr(global_flight, "_out_dir", str(tmp_path))
    monkeypatch.setattr(global_flight, "dumps", 0)
    monkeypatch.setattr(global_flight, "max_dumps", 1 << 20)
    yield


def _data(seed, n, f=F):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32).astype(np.float64)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    return X, y


PARAMS = {"objective": "binary", "verbosity": -1, "num_leaves": 15}


@pytest.fixture(scope="module")
def deployed():
    """One deployed model shared by every test: promotions swap FLEET
    entries, never this booster, and refreshed candidates copy the tree
    LIST (engine `_apply_init_model`), so no test can mutate it."""
    X, y = _data(0, 2000)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    b = lgb.train(PARAMS, ds, 6, verbose_eval=False)
    return b, ds, X


def _fleet(booster):
    # a short bucket ladder (8/16/32): canary warm() compiles every
    # bucket per candidate digest, and these tests promote a lot
    fl = lgb.Fleet(max_batch_rows=32)
    fl.add_model("live", booster)
    return fl


def _controller(fleet, tmp_path, chaos=None, **cfg):
    cfg.setdefault("drift_budget", 50.0)
    cfg.setdefault("mirror_fraction", 0.5)
    cfg.setdefault("ramp", (0.25, 0.5))
    return LifecycleController(
        fleet, "live", directory=str(tmp_path / "lc"),
        config=LifecycleConfig(**cfg), chaos=chaos)


def _dumps_named(tmp_path, token):
    return [d for d in os.listdir(tmp_path)
            if d.startswith("flight_lifecycle") and token in d]


# --------------------------------------------------------------- full cycle


def test_full_cycle_promotion_bit_parity(deployed, tmp_path):
    b, ds, X = deployed
    fleet = _fleet(b)
    try:
        ctl = _controller(fleet, tmp_path)
        Xf, yf = _data(1, 1000)
        bundle, cand = ctl.refresh(Xf, yf, params=PARAMS,
                                   num_boost_round=3)
        assert cand.current_iteration() == 9       # 6 warm + 3 fresh
        res = ctl.promote(bundle, probe_X=X[:64],
                          traffic=replay_traffic(X, requests=24))
        assert res["status"] == "promoted"
        # the fleet now serves the candidate BIT-identically
        served = fleet.predict("live", X[:32], timeout=120)
        assert np.array_equal(served,
                              cand.predict(X[:32], raw_score=True))
        assert fleet.entry("live").model.digest == booster_digest(cand)
        # the canary entry is gone; freshness was reset
        assert fleet.models() == ["live"]
        age = global_watchdog.model_age_s("live")
        assert age is not None and age < 60.0
        # journal records the promotion durably
        rec = ctl.journal.load()
        assert rec["status"] == "promoted"
        assert rec["candidate_digest"] == booster_digest(cand)
        # every phase was measured
        assert res["phases"]["shadow"]["mirrored"] > 0
        assert len(res["phases"]["ramp"]) == 2
    finally:
        fleet.close()


def test_second_refresh_after_promotion(deployed, tmp_path):
    """A promoted candidate is reloaded from model text; the controller
    must keep binning later refreshes on the ORIGINAL frozen grid."""
    b, ds, X = deployed
    fleet = _fleet(b)
    try:
        ctl = _controller(fleet, tmp_path)
        Xf, yf = _data(1, 1000)
        bundle, _ = ctl.refresh(Xf, yf, params=PARAMS, num_boost_round=3)
        res = ctl.promote(bundle, probe_X=X[:64],
                          traffic=replay_traffic(X, requests=16))
        assert res["status"] == "promoted"
        Xg, yg = _data(2, 1000)
        bundle2, cand2 = ctl.refresh(Xg, yg, num_boost_round=3)
        assert cand2.current_iteration() == 12     # 6 + 3 + 3
        res2 = ctl.promote(bundle2, probe_X=X[:64],
                           traffic=replay_traffic(X, requests=16))
        assert res2["status"] == "promoted"
        assert fleet.entry("live").model.digest == booster_digest(cand2)
    finally:
        fleet.close()


def test_streamed_chunked_refresh_byte_identical(deployed):
    """Fresh rows pushed chunk-by-chunk through the streaming plane
    (frozen-grid binning + push-time init scores) must train the SAME
    candidate as a resident refresh — byte-identical model text."""
    b, ds, X = deployed
    Xf, yf = _data(3, 1400)
    res_ds = fresh_dataset(ds, Xf, yf)
    cand_res = lgb.train(PARAMS, res_ds, 3, init_model=b,
                         verbose_eval=False)
    chunks = [(Xf[i:i + 500], yf[i:i + 500])      # ragged final chunk
              for i in range(0, 1400, 500)]
    str_ds = fresh_dataset(ds, chunks=iter(chunks), num_rows=1400,
                           predictor=b)
    cand_str = lgb.train(PARAMS, str_ds, 3, init_model=b,
                         verbose_eval=False)
    assert cand_res.model_to_string() == cand_str.model_to_string()


# ------------------------------------------------------------ gate breaches


def test_drift_gate_rolls_back_bit_identical(deployed, tmp_path):
    b, ds, X = deployed
    fleet = _fleet(b)
    try:
        ctl = _controller(fleet, tmp_path, drift_budget=1e-12,
                          mirror_fraction=1.0)
        Xf, yf = _data(1, 1000)
        bundle, _ = ctl.refresh(Xf, yf, params=PARAMS, num_boost_round=3)
        pre = fleet.predict("live", X[:32], timeout=120)
        res = ctl.promote(bundle, probe_X=X[:64],
                          traffic=replay_traffic(X, requests=16))
        assert res["status"] == "rolled_back" and res["gate"] == "drift"
        post = fleet.predict("live", X[:32], timeout=120)
        assert np.array_equal(pre, post)
        assert fleet.models() == ["live"]          # canary unregistered
        assert ctl.journal.load()["status"] == "rolled_back"
        # the forensic bundle names the gate and parses as JSON
        dumps = _dumps_named(tmp_path, "drift")
        assert dumps, os.listdir(tmp_path)
        bundle_json = json.load(open(tmp_path / dumps[0]))
        assert bundle_json["trigger"] == "lifecycle:drift"
        assert bundle_json["extra"]["gate"] == "drift"
        assert "traceEvents" in bundle_json["ring"]
    finally:
        fleet.close()


def test_chaos_corrupt_bundle_gate(deployed, tmp_path):
    """A candidate bundle torn by a chaos:// partial write must fail
    manifest verification and roll back before the candidate ever
    serves."""
    b, ds, X = deployed
    fleet = _fleet(b)
    chaos = ChaosRegistry("fs.partial@0", seed=0)
    chaos.install_filesystem()
    try:
        ctl = LifecycleController(
            fleet, "live", directory=f"chaos://{tmp_path}/lc",
            config=LifecycleConfig(drift_budget=50.0))
        Xf, yf = _data(1, 1000)
        # op 0 = the bundle write itself -> silently half-persisted
        bundle, _ = ctl.refresh(Xf, yf, params=PARAMS, num_boost_round=3)
        pre = fleet.predict("live", X[:32], timeout=120)
        res = ctl.promote(bundle, probe_X=X[:64],
                          traffic=replay_traffic(X, requests=8))
        assert res["status"] == "rolled_back"
        assert res["gate"] == "bundle-verify"
        assert np.array_equal(pre, fleet.predict("live", X[:32],
                                                 timeout=120))
        assert _dumps_named(tmp_path, "bundle-verify")
    finally:
        chaos.uninstall_filesystem()
        fleet.close()


def test_chaos_nan_candidate_gate(deployed, tmp_path):
    """NaN candidate outputs during shadow breach the nonfinite gate;
    callers never see the NaN (shadow mirrors are observation-only)."""
    b, ds, X = deployed
    fleet = _fleet(b)
    chaos = ChaosRegistry(
        ",".join(f"serving.nan@{i}" for i in range(16)), seed=0)
    try:
        ctl = _controller(fleet, tmp_path, chaos=chaos,
                          mirror_fraction=1.0)
        Xf, yf = _data(1, 1000)
        bundle, _ = ctl.refresh(Xf, yf, params=PARAMS, num_boost_round=3)
        pre = fleet.predict("live", X[:32], timeout=120)
        res = ctl.promote(bundle, probe_X=X[:64],
                          traffic=replay_traffic(X, requests=12))
        assert res["status"] == "rolled_back"
        assert res["gate"] == "nonfinite"
        assert np.array_equal(pre, fleet.predict("live", X[:32],
                                                 timeout=120))
        assert _dumps_named(tmp_path, "nonfinite")
    finally:
        fleet.close()


def test_chaos_latency_spike_mid_ramp(deployed, tmp_path):
    """A latency spike that begins mid-ramp (the shadow window was
    clean) must breach the p99 gate at that ramp step and roll back."""
    b, ds, X = deployed
    fleet = _fleet(b)
    # shadow mirrors ~12 candidate calls first (mirror_fraction 0.5 of
    # 24 requests); the spike starts strictly after that window
    chaos = ChaosRegistry(
        ",".join(f"serving.delay@{i}:sec=0.25" for i in range(14, 90)),
        seed=0)
    try:
        ctl = _controller(fleet, tmp_path, chaos=chaos,
                          mirror_fraction=0.5, p99_budget_ms=100.0,
                          ramp=(0.5,))
        Xf, yf = _data(1, 1000)
        bundle, _ = ctl.refresh(Xf, yf, params=PARAMS, num_boost_round=3)
        pre = fleet.predict("live", X[:32], timeout=120)
        res = ctl.promote(bundle, probe_X=X[:64],
                          traffic=replay_traffic(X, requests=24))
        assert res["status"] == "rolled_back"
        assert res["gate"] == "latency"
        assert res["evidence"]["phase"].startswith("ramp")
        assert np.array_equal(pre, fleet.predict("live", X[:32],
                                                 timeout=120))
        assert _dumps_named(tmp_path, "latency")
    finally:
        fleet.close()


def test_chaos_error_gate_degrades_to_live(deployed, tmp_path):
    """Hard candidate failures breach the error-rate gate — and every
    canary-routed request degraded to the live model instead of
    failing the caller."""
    b, ds, X = deployed
    fleet = _fleet(b)
    chaos = ChaosRegistry(
        ",".join(f"serving.error@{i}" for i in range(64)), seed=0)
    try:
        ctl = _controller(fleet, tmp_path, chaos=chaos,
                          mirror_fraction=1.0)
        Xf, yf = _data(1, 1000)
        bundle, _ = ctl.refresh(Xf, yf, params=PARAMS, num_boost_round=3)
        pre = fleet.predict("live", X[:32], timeout=120)
        res = ctl.promote(bundle, probe_X=X[:64],
                          traffic=replay_traffic(X, requests=12))
        assert res["status"] == "rolled_back"
        assert res["gate"] == "error-rate"
        assert np.array_equal(pre, fleet.predict("live", X[:32],
                                                 timeout=120))
    finally:
        fleet.close()


def test_probe_gate_never_registers_nan_candidate(deployed, tmp_path):
    """A candidate whose own predictions are non-finite is quarantined
    at the probe phase — before a canary entry ever exists."""
    b, ds, X = deployed
    fleet = _fleet(b)
    try:
        ctl = _controller(fleet, tmp_path)
        Xf, yf = _data(1, 1000)
        bundle, cand = ctl.refresh(Xf, yf, params=PARAMS,
                                   num_boost_round=3)
        # poison the banked bundle's leaf values: the reloaded
        # candidate predicts NaN on every row
        from lightgbm_tpu.resilience.checkpoint import (
            build_bundle_bytes, load_checkpoint)
        ck = load_checkpoint(bundle)
        cand.boosting.models[-1].leaf_value[:] = np.nan
        from lightgbm_tpu.utils.file_io import write_atomic
        write_atomic(bundle, build_bundle_bytes(
            cand, cand.current_iteration()))
        pre = fleet.predict("live", X[:32], timeout=120)
        res = ctl.promote(bundle, probe_X=X[:64],
                          traffic=replay_traffic(X, requests=8))
        assert res["status"] == "rolled_back" and res["gate"] == "probe"
        assert np.array_equal(pre, fleet.predict("live", X[:32],
                                                 timeout=120))
        assert fleet.models() == ["live"]
        assert ck.iteration == 9
    finally:
        fleet.close()


# ------------------------------------------------------------- crash/resume


def test_crash_resume_mid_ramp_rolls_back(deployed, tmp_path):
    """A pipeline killed between ramp steps leaves an in_progress
    journal and a stale canary; a fresh controller's resume() must
    clean both up and keep the fleet serving the old model
    bit-identically."""
    b, ds, X = deployed
    Xf, yf = _data(1, 1000)
    cand = lgb.train(PARAMS, fresh_dataset(ds, Xf, yf), 4,
                     init_model=b, verbose_eval=False)
    mgr = CheckpointManager(str(tmp_path / "lc"), prefix="lifecycle")
    bundle = mgr.save(cand, iteration=cand.current_iteration())
    # the "crashed" process: journal parked at ramp step 0, canary
    # still registered
    j = RolloutJournal(str(tmp_path / "lc" / "rollout.json"))
    rec = j.begin("live", bundle, booster_digest(cand), None,
                  booster_digest(b), (0.25, 0.5))
    j.phase(rec, "ramp", 0)
    fleet = _fleet(b)
    fleet.add_model("live" + CANARY_SUFFIX, cand, weight=0.1)
    try:
        pre = b.predict(X[:32], raw_score=True)
        ctl = _controller(fleet, tmp_path)
        out = ctl.resume()
        assert out["status"] == "rolled_back"
        assert out["gate"] == "crash-resume"
        assert fleet.models() == ["live"]
        assert np.array_equal(pre, fleet.predict("live", X[:32],
                                                 timeout=120))
        assert ctl.journal.load()["status"] == "rolled_back"
        # idempotent: a second resume is a no-op
        assert ctl.resume()["status"] == "idle"
    finally:
        fleet.close()


def test_resume_finishes_committed_cutover(deployed, tmp_path):
    """A crash AFTER the swap landed but BEFORE the journal recorded
    ``promoted``: resume() must finish the promotion (the live digest
    is the commit witness) — and never swap again."""
    b, ds, X = deployed
    Xf, yf = _data(1, 1000)
    cand = lgb.train(PARAMS, fresh_dataset(ds, Xf, yf), 4,
                     init_model=b, verbose_eval=False)
    mgr = CheckpointManager(str(tmp_path / "lc"), prefix="lifecycle")
    bundle = mgr.save(cand, iteration=cand.current_iteration())
    j = RolloutJournal(str(tmp_path / "lc" / "rollout.json"))
    rec = j.begin("live", bundle, booster_digest(cand), None,
                  booster_digest(b), (0.25,))
    j.phase(rec, "cutover")
    fleet = _fleet(cand)           # the flip already landed
    try:
        ctl = _controller(fleet, tmp_path)
        swaps_before = fleet.entry("live").server.metrics.to_dict()[
            "counters"].get("hot_swaps", 0)
        out = ctl.resume()
        assert out["status"] == "promoted" and out["resumed"]
        assert ctl.journal.load()["status"] == "promoted"
        swaps_after = fleet.entry("live").server.metrics.to_dict()[
            "counters"].get("hot_swaps", 0)
        assert swaps_after == swaps_before     # no double-promotion
        assert np.array_equal(
            fleet.predict("live", X[:32], timeout=120),
            cand.predict(X[:32], raw_score=True))
    finally:
        fleet.close()


def test_resume_uncommitted_cutover_restores_previous(deployed, tmp_path):
    """A crash after journaling the cutover intent but BEFORE the flip:
    the live digest is not the candidate's, so resume() rolls back."""
    b, ds, X = deployed
    Xf, yf = _data(1, 1000)
    cand = lgb.train(PARAMS, fresh_dataset(ds, Xf, yf), 4,
                     init_model=b, verbose_eval=False)
    mgr = CheckpointManager(str(tmp_path / "lc"), prefix="lifecycle")
    bundle = mgr.save(cand, iteration=cand.current_iteration())
    j = RolloutJournal(str(tmp_path / "lc" / "rollout.json"))
    rec = j.begin("live", bundle, booster_digest(cand), None,
                  booster_digest(b), (0.25,))
    j.phase(rec, "cutover")
    fleet = _fleet(b)              # flip never landed
    try:
        pre = fleet.predict("live", X[:32], timeout=120)
        ctl = _controller(fleet, tmp_path)
        out = ctl.resume()
        assert out["status"] == "rolled_back"
        assert np.array_equal(pre, fleet.predict("live", X[:32],
                                                 timeout=120))
    finally:
        fleet.close()


def test_pipeline_error_after_flip_unflips(deployed, tmp_path):
    """An unexpected failure AFTER the cutover swap committed (here:
    the journal's promoted write dies) must still roll the live pointer
    back — with the REAL candidate digest from the live journal record,
    and the in-memory pre-promotion booster as the anchor when no older
    verified bundle exists (a first promotion)."""
    b, ds, X = deployed
    fleet = _fleet(b)
    try:
        ctl = _controller(fleet, tmp_path)
        Xf, yf = _data(1, 1000)
        bundle, _ = ctl.refresh(Xf, yf, params=PARAMS, num_boost_round=3)
        pre = fleet.predict("live", X[:32], timeout=120)

        def boom(rec):
            raise RuntimeError("journal write died post-flip")

        ctl.journal.promoted = boom
        with pytest.raises(RuntimeError, match="post-flip"):
            ctl.promote(bundle, probe_X=X[:64],
                        traffic=replay_traffic(X, requests=12))
        rec = ctl.journal.load()
        assert rec["status"] == "rolled_back"
        assert rec["gate"] == "pipeline-error"
        assert rec["candidate_digest"]             # NOT the stale ""
        assert rec["phase"] == "cutover"
        assert np.array_equal(pre, fleet.predict("live", X[:32],
                                                 timeout=120))
        assert fleet.models() == ["live"]
    finally:
        fleet.close()


def test_config_rejects_degenerate_ramp():
    with pytest.raises(ValueError, match="ramp fractions"):
        LifecycleConfig(ramp=())
    with pytest.raises(ValueError, match="ramp fractions"):
        LifecycleConfig(ramp=(1.5,))
    with pytest.raises(ValueError, match="mirror_fraction"):
        LifecycleConfig(mirror_fraction=1.5)


def test_journal_refuses_concurrent_rollout(tmp_path):
    j = RolloutJournal(str(tmp_path / "rollout.json"))
    rec = j.begin("live", "b1", "d1", None, "d0", (0.5,))
    with pytest.raises(RolloutJournalError, match="in_progress"):
        j.begin("live", "b2", "d2", None, "d0", (0.5,))
    j.rolled_back(rec, "drift", {})
    j.begin("live", "b2", "d2", None, "d0", (0.5,))   # now fine


# ------------------------------------------------- rollback pin (before=)


def test_latest_verified_before_pins_older_bundle(deployed, tmp_path):
    b, ds, X = deployed
    mgr = CheckpointManager(str(tmp_path / "ck"), prefix="lifecycle",
                            keep_last=5)
    p1 = mgr.save(b, iteration=8)
    Xf, yf = _data(1, 1000)
    cand = lgb.train(PARAMS, fresh_dataset(ds, Xf, yf), 2,
                     init_model=b, verbose_eval=False)
    p2 = mgr.save(cand, iteration=10)
    cand2 = lgb.train(PARAMS, fresh_dataset(ds, Xf, yf), 4,
                      init_model=b, verbose_eval=False)
    p3 = mgr.save(cand2, iteration=12)
    # unpinned: newest wins
    assert mgr.latest_verified().iteration == 12
    # pinned below the "failed candidate" p2: p1 wins even though a
    # NEWER verified bundle (p3, a concurrent save) exists
    assert mgr.latest_verified(before=p2).iteration == 8
    assert mgr.latest_verified(before=os.path.basename(p2)).iteration == 8
    # an iteration number pins the same way
    assert mgr.latest_verified(before=12).iteration == 10
    with pytest.raises(CheckpointNotFoundError):
        mgr.latest_verified(before=p1)
    assert p3.endswith(".lgbckpt")


# --------------------------------------------------------------- freshness


def test_freshness_slo_breach_dumps(tmp_path):
    reg = MetricsRegistry()
    fl = FlightRecorder(enabled=True, out_dir=str(tmp_path / "fd"),
                        max_dumps=4)
    os.makedirs(tmp_path / "fd", exist_ok=True)
    wd = Watchdog(SLOConfig(model_age_max_s=0.005), registry=reg,
                  flight=fl)
    wd.watch_freshness("m")
    import time
    time.sleep(0.02)
    breaches = wd.check_once()
    assert [s for s, _ in breaches] == ["freshness:m"]
    # gauge published, counter bumped, bundle written on the rising edge
    d = reg.to_dict()
    assert d["gauges"]['model_age_seconds{model="m"}'] > 0
    assert d["counters"]['slo_breach_total{slo="freshness:m"}'] == 1
    dumps = os.listdir(tmp_path / "fd")
    assert len(dumps) == 1 and "freshness" in dumps[0]
    # persistent breach: no dump storm (edge-triggered)
    wd.check_once()
    assert len(os.listdir(tmp_path / "fd")) == 1
    # mark_fresh clears the breach
    wd.mark_fresh("m")
    assert wd.check_once() == []
    assert wd.model_age_s("m") < 1.0
    wd.unwatch_freshness("m")
    assert wd.check_once() == []


def test_freshness_age_resets_on_promotion(deployed, tmp_path):
    b, ds, X = deployed
    fleet = _fleet(b)
    try:
        ctl = _controller(fleet, tmp_path)
        # simulate a stale deployment, then promote
        with global_watchdog._lock:
            ts, cap = global_watchdog._fresh["live"]
            global_watchdog._fresh["live"] = (ts - 10_000.0, cap)
        assert global_watchdog.model_age_s("live") > 9_000
        Xf, yf = _data(1, 1000)
        bundle, _ = ctl.refresh(Xf, yf, params=PARAMS, num_boost_round=3)
        res = ctl.promote(bundle, probe_X=X[:64],
                          traffic=replay_traffic(X, requests=12))
        assert res["status"] == "promoted"
        assert global_watchdog.model_age_s("live") < 60.0
    finally:
        fleet.close()


# ----------------------------------------------------- init-model satellite


def test_init_model_feature_mismatch_named_error(deployed):
    b, ds, X = deployed
    Xw, yw = _data(1, 400, f=F + 2)
    with pytest.raises(InitModelCompatibilityError, match="features"):
        lgb.train(PARAMS, lgb.Dataset(Xw, label=yw,
                                      free_raw_data=False), 2,
                  init_model=b, verbose_eval=False)


def test_init_model_class_mismatch_named_error(deployed):
    b, ds, X = deployed
    rng = np.random.RandomState(2)
    ym = rng.randint(0, 3, 400).astype(float)
    with pytest.raises(InitModelCompatibilityError, match="per iteration"):
        lgb.train({"objective": "multiclass", "num_class": 3,
                   "verbosity": -1},
                  lgb.Dataset(_data(1, 400)[0], label=ym,
                              free_raw_data=False), 2,
                  init_model=b, verbose_eval=False)


def test_init_model_cross_load_from_model_text(deployed, tmp_path):
    """Warm-start from saved model TEXT (the stock-LightGBM cross-load
    path) must match warm-starting from the in-process Booster
    byte-for-byte."""
    b, ds, X = deployed
    path = str(tmp_path / "deployed.txt")
    b.save_model(path)
    Xf, yf = _data(1, 1000)
    from_obj = lgb.train(PARAMS, lgb.Dataset(Xf, label=yf,
                                             free_raw_data=False), 3,
                         init_model=b, verbose_eval=False)
    from_txt = lgb.train(PARAMS, lgb.Dataset(Xf, label=yf,
                                             free_raw_data=False), 3,
                         init_model=path, verbose_eval=False)
    assert from_obj.current_iteration() == 9
    assert from_txt.model_to_string() == from_obj.model_to_string()


# ------------------------------------------------------- loadgen satellite


def test_loadgen_shadow_mode_summary(deployed):
    from lightgbm_tpu.serving.loadgen import fire_requests
    b, ds, X = deployed
    Xf, yf = _data(1, 1000)
    cand = lgb.train(PARAMS, fresh_dataset(ds, Xf, yf), 4,
                     init_model=b, verbose_eval=False)
    live = b.serve(max_batch_rows=128)
    shadow = cand.serve(max_batch_rows=128)
    try:
        storm = fire_requests(live, 40, 4, 32, F, timeout=120,
                              shadow_server=shadow, mirror_fraction=0.5)
        # live accounting is honest: every planned request completed on
        # the live path regardless of mirroring
        assert storm["requests"] == storm["requests_planned"] == 40
        assert storm["shed"] == 0 and storm["expired"] == 0
        assert not storm["errors"]
        assert storm["latency_ms"]["count"] == 40
        sh = storm["shadow"]
        assert 0 < sh["mirrored"] < 40
        assert sh["drift_max"] is not None and sh["drift_max"] > 0
        assert sh["nonfinite"] == 0 and not sh["errors"]
        assert sh["latency_ms"]["count"] == sh["mirrored"]
        assert sh["latency_delta_ms"]["count"] == sh["mirrored"]
    finally:
        live.close()
        shadow.close()


def test_loadgen_without_shadow_unchanged(deployed):
    from lightgbm_tpu.serving.loadgen import fire_requests
    b, ds, X = deployed
    n_iter = len(b.models) // b.num_tree_per_iteration
    live = b.serve(max_batch_rows=128)
    try:
        storm = fire_requests(live, 20, 4, 32, F, timeout=120,
                              verify_forest=b._forest(0, n_iter))
        assert storm["requests"] == storm["requests_planned"]
        assert storm["mismatches"] == []
        assert "shadow" not in storm
    finally:
        live.close()


# ------------------------------------------------------------- smoke driver


@pytest.mark.slow
def test_lifecycle_smoke_tool(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from lifecycle_smoke import run_smoke
    summary = run_smoke(rows=3000, trees=6, refresh_trees=3,
                        requests=32, threads=2,
                        directory=str(tmp_path / "smoke"))
    assert not summary["failed"], summary["phase_ok"]
