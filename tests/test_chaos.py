"""Chaos-injection tests: resilient collectives and file-system faults.

Each test drives the REAL seams — the ``allgather_bytes`` injection
point of parallel/dist_data.py and the pluggable file system of
utils/file_io.py — through ``resilience.faults.ChaosRegistry`` with a
deterministic, seeded schedule (syntax: docs/RESILIENCE.md).

Acceptance bar exercised here: under injected allgather faults (drop,
truncation, bit-flip) the fake-mesh ``distributed_bin_mappers`` either
completes after retries or aborts consistently on every rank within the
configured deadline — never hangs, never silently uses a corrupted
payload.  Long stress variants are ``slow``; everything carries the
``chaos`` marker.
"""
import threading
import time

import numpy as np
import pytest

from lightgbm_tpu.parallel.dist_data import (distributed_bin_mappers,
                                             make_fake_allgather)
from lightgbm_tpu.resilience import (ChaosRegistry, CollectiveError,
                                     ResilienceConfig, parse_schedule,
                                     resilient_allgather)

pytestmark = pytest.mark.chaos

WORLD = 4
CFG = ResilienceConfig(deadline_s=20.0, max_retries=5, base_backoff_s=0.01)


def _run_ranks(fn, world=WORLD, join_s=120):
    """fn(rank) on one thread per rank; returns (results, errors)."""
    out, errs = [None] * world, [None] * world

    def runner(k):
        try:
            out[k] = fn(k)
        except Exception as e:  # noqa: BLE001 — asserted by callers
            errs[k] = e

    threads = [threading.Thread(target=runner, args=(k,))
               for k in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(join_s)
    assert not any(t.is_alive() for t in threads), "a rank is HUNG"
    return out, errs


def _gather(chaos=None, cfg=CFG, mesh_timeout=2.0, world=WORLD):
    fake = make_fake_allgather(world, timeout=mesh_timeout)

    def fn(k):
        ag = fake(k)
        if chaos is not None:
            ag = chaos.wrap_allgather(ag, k)
        return resilient_allgather(f"rank{k}".encode(), ag, world=world,
                                   rank=k, config=cfg)

    return _run_ranks(fn, world)


EXPECT = [f"rank{k}".encode() for k in range(WORLD)]


def test_clean_transport_single_attempt():
    out, errs = _gather()
    assert errs == [None] * WORLD
    assert all(o == EXPECT for o in out)


@pytest.mark.parametrize("kind", ["bitflip", "truncate", "drop"])
def test_send_faults_recover_after_retry(kind):
    chaos = ChaosRegistry(f"allgather.{kind}@0:rank=1", seed=0)
    out, errs = _gather(chaos)
    assert errs == [None] * WORLD
    assert all(o == EXPECT for o in out), \
        "a rank consumed a corrupted payload"
    assert chaos.log == [f"allgather[1].{kind}@0"]


def test_recv_corruption_forces_rank_consistent_retry():
    """Corruption visible to ONE receiver must make every rank retry via
    the verdict round — no rank may run ahead with clean data another
    rank rejected."""
    chaos = ChaosRegistry("allgather.recv_bitflip@0:rank=3", seed=0)
    out, errs = _gather(chaos)
    assert errs == [None] * WORLD
    assert all(o == EXPECT for o in out)


def test_delay_fault_is_transparent():
    chaos = ChaosRegistry("allgather.delay@0:sec=0.05", seed=0)
    out, errs = _gather(chaos)
    assert errs == [None] * WORLD
    assert all(o == EXPECT for o in out)


def test_stall_aborts_consistently_within_deadline():
    chaos = ChaosRegistry("allgather.stall@0:rank=0:sec=60", seed=0)
    cfg = ResilienceConfig(deadline_s=2.5, max_retries=10,
                           base_backoff_s=0.01)
    t0 = time.monotonic()
    out, errs = _gather(chaos, cfg=cfg, mesh_timeout=0.4)
    elapsed = time.monotonic() - t0
    assert all(isinstance(e, CollectiveError) for e in errs), errs
    assert elapsed < cfg.deadline_s + 8.0, "abort was not deadline-bounded"


# --------------------------------------------------------- bin mappers


def _bin_data():
    rng = np.random.RandomState(0)
    X = rng.rand(2000, 5)
    bounds = np.linspace(0, len(X), WORLD + 1).astype(int)
    return X, bounds


def _run_mappers(chaos, cfg, mesh_timeout=2.0):
    X, bounds = _bin_data()
    fake = make_fake_allgather(WORLD, timeout=mesh_timeout)

    def fn(k):
        ag = fake(k)
        if chaos is not None:
            ag = chaos.wrap_allgather(ag, k)
        return distributed_bin_mappers(X[bounds[k]:bounds[k + 1]], params={},
                                       rank=k, world=WORLD,
                                       allgather_bytes=ag, resilience=cfg)

    return _run_ranks(fn)


def _assert_mappers_equal(a, b):
    for m, n in zip(a[0], b[0]):
        assert m.num_bin == n.num_bin
        np.testing.assert_array_equal(m.bin_upper_bound, n.bin_upper_bound)


def test_bin_mappers_complete_under_faults():
    clean, errs = _run_mappers(None, None)
    assert errs == [None] * WORLD
    chaos = ChaosRegistry(
        "allgather.bitflip@0:rank=0,allgather.truncate@4:rank=2,"
        "allgather.drop@2:rank=3", seed=0)
    faulted, errs = _run_mappers(chaos, CFG)
    assert errs == [None] * WORLD
    for r in range(WORLD):
        _assert_mappers_equal(faulted[r], clean[0])
        assert faulted[r][2] == clean[0][2]    # total_sample_cnt
    assert len(chaos.log) == 3


def test_bin_mappers_dead_transport_aborts_all_ranks():
    dead = ",".join(f"allgather.stall@{i}:rank=1:sec=60" for i in range(50))
    cfg = ResilienceConfig(deadline_s=3.0, max_retries=30,
                           base_backoff_s=0.01)
    t0 = time.monotonic()
    _, errs = _run_mappers(ChaosRegistry(dead, seed=0), cfg,
                           mesh_timeout=0.4)
    assert all(isinstance(e, CollectiveError) for e in errs), errs
    assert time.monotonic() - t0 < cfg.deadline_s + 10.0


def test_bin_mappers_degraded_fallback_is_loud_and_completes():
    dead = ",".join(f"allgather.stall@{i}:rank=1:sec=60" for i in range(50))
    cfg = ResilienceConfig(deadline_s=3.0, max_retries=30,
                           base_backoff_s=0.01, degraded_fallback=True)
    out, errs = _run_mappers(ChaosRegistry(dead, seed=0), cfg,
                             mesh_timeout=0.4)
    assert errs == [None] * WORLD
    assert all(len(o[0]) == 5 for o in out)    # every rank got mappers


def test_resilience_config_from_params():
    assert ResilienceConfig.from_params({}) is None
    cfg = ResilienceConfig.from_params(
        {"network_resilience": True, "network_deadline": 7.5,
         "network_retries": 2, "network_degraded_fallback": True})
    assert cfg.deadline_s == 7.5 and cfg.max_retries == 2
    assert cfg.degraded_fallback


def test_parse_schedule_syntax():
    specs = parse_schedule(
        "allgather.bitflip@2:rank=1,fs.enospc@0,"
        "allgather.delay@1:sec=0.25:prob=0.5")
    assert [s.kind for s in specs] == ["bitflip", "enospc", "delay"]
    assert specs[0].rank == 1 and specs[0].at == 2
    assert specs[2].arg == 0.25 and specs[2].prob == 0.5
    with pytest.raises(ValueError):
        parse_schedule("allgather.explode@0")
    with pytest.raises(ValueError):
        parse_schedule("disk.enospc@0")


# ----------------------------------------------------------- fs faults


def test_fs_transient_and_partial_write(tmp_path):
    import lightgbm_tpu as lgb
    from lightgbm_tpu.dataset import Dataset
    from lightgbm_tpu.resilience import CheckpointManager
    rng = np.random.RandomState(0)
    X = rng.rand(300, 5)
    y = (X[:, 0] > 0.5).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    Dataset(X, label=y), 4, verbose_eval=False)
    chaos = ChaosRegistry("fs.transient@0", seed=0)
    chaos.install_filesystem("chaos")
    try:
        mgr = CheckpointManager(f"chaos://{tmp_path}/ck", keep_last=3)
        with pytest.raises(OSError):
            mgr.save(bst, 2)            # transient error surfaces
        mgr.save(bst, 2)                # retry succeeds
        mgr.save(bst, 4)
        assert mgr.latest_verified().iteration == 4
    finally:
        chaos.uninstall_filesystem()

    # a silent partial write of the newest bundle (the crash-mid-write
    # shape on a non-atomic backend) must be caught by the manifest and
    # fall back to the previous good bundle
    chaos = ChaosRegistry("fs.partial@0", seed=0)
    chaos.install_filesystem("chaos")
    try:
        mgr = CheckpointManager(f"chaos://{tmp_path}/ck", keep_last=3)
        mgr.save(bst, 6)                # silently truncated on disk
        assert mgr.latest_verified().iteration == 4
    finally:
        chaos.uninstall_filesystem()


def test_fs_enospc_leaves_prior_state_intact(tmp_path):
    import lightgbm_tpu as lgb
    from lightgbm_tpu.dataset import Dataset
    from lightgbm_tpu.resilience import CheckpointManager
    rng = np.random.RandomState(0)
    X = rng.rand(300, 5)
    y = (X[:, 0] > 0.5).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    Dataset(X, label=y), 4, verbose_eval=False)
    chaos = ChaosRegistry("fs.enospc@2", seed=0)
    chaos.install_filesystem("chaos")
    try:
        mgr = CheckpointManager(f"chaos://{tmp_path}/ck2", keep_last=3)
        mgr.save(bst, 2)                # ops 0-1 (bundle + index) ok ...
        with pytest.raises(OSError):    # ... op 2, next bundle, ENOSPC
            mgr.save(bst, 4)
        assert mgr.latest_verified().iteration == 2
    finally:
        chaos.uninstall_filesystem()


# -------------------------------------------------------- slow stress


@pytest.mark.slow
def test_stress_random_faults_never_corrupt(tmp_path):
    """Probabilistic fault spray over many rounds: every completed
    gather is correct on every rank; failures only ever surface as
    CollectiveError."""
    spray = ",".join(
        f"allgather.bitflip@{i}:rank={i % WORLD}:prob=0.3" for i in range(60))
    chaos = ChaosRegistry(spray, seed=7)
    fake = make_fake_allgather(WORLD, timeout=2.0)
    cfg = ResilienceConfig(deadline_s=30.0, max_retries=8,
                           base_backoff_s=0.005)

    def fn(k):
        ag = chaos.wrap_allgather(fake(k), k)
        outs = []
        for round_i in range(6):
            outs.append(resilient_allgather(
                f"r{k}i{round_i}".encode(), ag, world=WORLD, rank=k,
                config=cfg))
        return outs

    out, errs = _run_ranks(fn, join_s=240)
    assert errs == [None] * WORLD
    for k in range(WORLD):
        for round_i, got in enumerate(out[k]):
            assert got == [f"r{q}i{round_i}".encode() for q in range(WORLD)]


@pytest.mark.slow
def test_stress_checkpoint_chaos_train_resume(tmp_path):
    """Full chaos_smoke-shaped loop: train under a partial-write fs
    fault, verify fallback resume still reaches the bit-identical final
    model."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.dataset import Dataset
    rng = np.random.RandomState(1)
    X = rng.rand(500, 8)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    P = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "bagging_fraction": 0.8, "bagging_freq": 1, "min_data_in_leaf": 5}
    full = lgb.train(P, Dataset(X, label=y), 20, verbose_eval=False)
    full.save_model(str(tmp_path / "full.txt"))

    chaos = ChaosRegistry("fs.partial@8", seed=0)   # corrupt a later write
    chaos.install_filesystem("chaos")
    try:
        lgb.train(P, Dataset(X, label=y), 12, verbose_eval=False,
                  snapshot_freq=2,
                  snapshot_out=f"chaos://{tmp_path}/m.txt")
    finally:
        chaos.uninstall_filesystem()
    res = lgb.train(P, Dataset(X, label=y), 20, verbose_eval=False,
                    resume_from=str(tmp_path / "m.txt.ckpt"))
    res.save_model(str(tmp_path / "res.txt"))
    assert (tmp_path / "full.txt").read_bytes() == \
        (tmp_path / "res.txt").read_bytes()
