"""Pod-scale parallel plane (parallel/collectives.py, hybrid ICI x DCN mesh).

The hard contracts, on the virtual 8-device CPU mesh (conftest.py):

- hierarchical (2-tier) reduction == flat psum — BYTE-identical model
  text for quantized payloads across {2x4, 4x2} simulated slice shapes
  (integer associativity), and f32 model-text-identical under the pinned
  tier-ordered reduction (LGBM_TPU_PINNED_REDUCE);
- voting-parallel's DCN bytes sit strictly below data-parallel's at
  equal trees on the same workload (ops/planner.plan_collectives);
- a preempted slice (seeded chaos over the allgather seam) resumes from
  the latest verified checkpoint bundle on a re-planned SMALLER mesh
  with eval history intact (resilience/elastic.py).
"""

import json
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.planner import plan_collectives
from lightgbm_tpu.parallel import network as net
from lightgbm_tpu.parallel.collectives import (DCN_AXIS, HYBRID_AXES,
                                               ICI_AXIS, axis_index_flat,
                                               axis_size, psum_int_tiered,
                                               psum_tiered)
from lightgbm_tpu.parallel.learners import (DATA_AXIS, data_axis_of,
                                            make_hybrid_mesh, make_mesh,
                                            shard_map_compat)
from lightgbm_tpu.resilience import (ChaosRegistry, ResilienceConfig,
                                     SliceLostError, apply_world,
                                     membership_probe, plan_shrunk_world,
                                     shrink_and_resume)
from lightgbm_tpu.parallel.dist_data import make_fake_allgather

pytestmark = pytest.mark.multihost

RNG = np.random.RandomState(7)
# n NOT divisible by 8 on purpose: every mesh width pads differently, so
# the elastic resume's row re-tiling (gbdt.restore_state) is exercised
N, F = 1201, 10
X = RNG.randn(N, F).astype(np.float32)
Y = (X[:, 0] + 0.5 * X[:, 3] ** 2 + 0.1 * RNG.randn(N) > 0.5).astype(
    np.float32)
XV = RNG.randn(301, F).astype(np.float32)
YV = (XV[:, 0] + 0.5 * XV[:, 3] ** 2 > 0.5).astype(np.float32)

BASE = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
        "max_bin": 63, "min_data_in_leaf": 5, "verbosity": -1,
        "tree_learner": "data"}
QUANT = {"use_quantized_grad": True, "num_grad_quant_bins": 16}


def _train(monkeypatch, *, slices=0, hier=None, pinned=False, rounds=8,
           extra=None):
    """One engine run under the given simulated-slice topology; returns
    (model_text, booster)."""
    for k in ("LGBM_TPU_NUM_SLICES", "LGBM_TPU_HIER_REDUCE",
              "LGBM_TPU_PINNED_REDUCE"):
        monkeypatch.delenv(k, raising=False)
    if slices:
        monkeypatch.setenv("LGBM_TPU_NUM_SLICES", str(slices))
    if hier is not None:
        monkeypatch.setenv("LGBM_TPU_HIER_REDUCE", "1" if hier else "0")
    if pinned:
        monkeypatch.setenv("LGBM_TPU_PINNED_REDUCE", "1")
    params = dict(BASE, **(extra or {}))
    ds = lgb.Dataset(X, label=Y, free_raw_data=False)
    bst = lgb.train(params, ds, num_boost_round=rounds, verbose_eval=False)
    return bst.model_to_string(), bst


# ---------------------------------------------------------------- mesh


def test_make_hybrid_mesh_shapes():
    for s in (2, 4):
        mesh = make_hybrid_mesh(8, num_slices=s)
        assert mesh.axis_names == HYBRID_AXES
        assert int(mesh.shape[DCN_AXIS]) == s
        assert int(mesh.shape[ICI_AXIS]) == 8 // s
        assert data_axis_of(mesh) == HYBRID_AXES
        assert axis_size(mesh, HYBRID_AXES) == 8
        # row-major over (slice, device-in-slice): same linear device
        # order as the flat mesh, so shard CONTENTS never move when the
        # hybrid mesh is elected (the parity tests lean on this)
        flat = make_mesh(8, (DATA_AXIS,))
        assert [d.id for d in mesh.devices.ravel()] \
            == [d.id for d in flat.devices.ravel()]
    assert data_axis_of(make_mesh(8, (DATA_AXIS,))) == DATA_AXIS


def test_make_hybrid_mesh_rejects_non_dividing():
    with pytest.raises(ValueError, match="partition"):
        make_hybrid_mesh(8, num_slices=3)


def test_mesh_plan_priority(monkeypatch):
    monkeypatch.delenv("LGBM_TPU_NUM_SLICES", raising=False)
    monkeypatch.delenv("LGBM_TPU_SLICE_DEVICES", raising=False)
    flat = net.mesh_plan(8)
    assert (flat.num_slices, flat.total_shards, flat.hybrid) == (1, 8, False)
    assert flat.source == "flat"
    # simulated slices env
    monkeypatch.setenv("LGBM_TPU_NUM_SLICES", "2")
    mp = net.mesh_plan(8)
    assert (mp.num_slices, mp.devices_per_slice, mp.source) == (2, 4, "env")
    # env additionally bounded by per-slice device count: the elastic
    # shrink's way to express a smaller surviving world
    monkeypatch.setenv("LGBM_TPU_SLICE_DEVICES", "2")
    mp = net.mesh_plan(8)
    assert (mp.num_slices, mp.devices_per_slice, mp.total_shards) \
        == (2, 2, 4)
    monkeypatch.delenv("LGBM_TPU_NUM_SLICES")
    monkeypatch.delenv("LGBM_TPU_SLICE_DEVICES")
    # num_machines steers the DCN tier when it divides the device count
    mp = net.mesh_plan(8, num_machines=4)
    assert (mp.num_slices, mp.devices_per_slice, mp.source) \
        == (4, 2, "num_machines")
    # ... and degrades to a flat capped mesh (loudly) when it doesn't
    mp = net.mesh_plan(8, num_machines=3)
    assert (mp.num_slices, mp.total_shards) == (1, 3)


def test_mesh_plan_mismatch_warns(monkeypatch, capsys):
    # a verbosity=-1 run earlier in the session silences warnings
    # globally; the loud-mismatch contract is about the DEFAULT level
    monkeypatch.setattr("lightgbm_tpu.utils.log._current_level", 1)
    monkeypatch.setenv("LGBM_TPU_NUM_SLICES", "2")
    mp = net.mesh_plan(8, num_machines=5, local_listen_port=12399)
    assert mp.num_slices == 2
    err = capsys.readouterr().err
    assert "num_machines=5 disagrees" in err
    assert "12399" in err


def test_init_network_roundtrips_into_mesh_plan(monkeypatch):
    # a single-machine non-dry-run call records itself without touching
    # jax.distributed; mesh_plan then consults the recorded call
    assert net.last_network_init() is None or True  # state may linger
    net.init_network(machines="127.0.0.1:12400", num_machines=1,
                     local_listen_port=12400)
    rec = net.last_network_init()
    assert rec is not None and rec["num_machines"] == 1
    assert rec["local_listen_port"] == 12400
    net.free_network()
    assert net.last_network_init() is None
    # mesh_plan falls back to the recorded init when no explicit
    # num_machines is passed
    monkeypatch.delenv("LGBM_TPU_NUM_SLICES", raising=False)
    monkeypatch.setattr(net, "_LAST_INIT",
                        {"num_machines": 4, "local_listen_port": 12401})
    mp = net.mesh_plan(8)
    assert (mp.num_slices, mp.source) == (4, "num_machines")


def test_create_parallel_grower_mismatch_warns(monkeypatch, capsys):
    monkeypatch.setattr("lightgbm_tpu.utils.log._current_level", 1)
    from lightgbm_tpu.dataset import FeatureMeta
    from lightgbm_tpu.grower import GrowerConfig
    from lightgbm_tpu.ops.split import SplitHyperparams
    from lightgbm_tpu.parallel.learners import create_parallel_grower
    meta = FeatureMeta(num_bin=np.full(F, 16, np.int32),
                       missing_type=np.zeros(F, np.int32),
                       default_bin=np.zeros(F, np.int32),
                       most_freq_bin=np.zeros(F, np.int32),
                       is_categorical=np.zeros(F, bool), max_num_bin=16)
    cfg = GrowerConfig(num_leaves=7, hp=SplitHyperparams(), num_bins=16,
                       num_machines=5)
    create_parallel_grower("data", make_mesh(8, (DATA_AXIS,)), meta, cfg)
    assert "num_machines=5 disagrees" in capsys.readouterr().err


# ---------------------------------------------------- collective prims


@pytest.mark.parametrize("slices", [2, 4])
def test_tiered_psum_matches_flat(slices):
    mesh = make_hybrid_mesh(8, num_slices=slices)
    xf = np.arange(8 * 24, dtype=np.float32).reshape(8, 24) * 0.37
    xi = np.arange(8 * 24, dtype=np.int32).reshape(8, 24) - 91

    def run(body, arr):
        f = shard_map_compat(body, mesh=mesh, in_specs=(P(HYBRID_AXES),),
                             out_specs=P(HYBRID_AXES), check_vma=False)
        return np.asarray(jax.jit(f)(jnp.asarray(arr)))

    flat_f = run(lambda v: psum_tiered(v, HYBRID_AXES), xf)
    hier_f = run(lambda v: psum_tiered(v, HYBRID_AXES, hierarchical=True),
                 xf)
    np.testing.assert_allclose(hier_f, flat_f, rtol=1e-6)
    np.testing.assert_allclose(flat_f[0], xf.sum(axis=0), rtol=1e-6)
    # pinned: flat and hierarchical arms share ONE tier-ordered
    # association, so they agree bitwise
    pin_flat = run(lambda v: psum_tiered(v, HYBRID_AXES, pinned=True), xf)
    pin_hier = run(lambda v: psum_tiered(v, HYBRID_AXES, hierarchical=True,
                                         pinned=True), xf)
    np.testing.assert_array_equal(pin_flat, pin_hier)
    # integers: exact under every schedule, narrowed or not
    flat_i = run(lambda v: psum_int_tiered(v, HYBRID_AXES), xi)
    hier_i = run(lambda v: psum_int_tiered(v, HYBRID_AXES,
                                           hierarchical=True), xi)
    nar_i = run(lambda v: psum_int_tiered(v, HYBRID_AXES, hierarchical=True,
                                          narrow=jnp.int16), xi)
    np.testing.assert_array_equal(flat_i, hier_i)
    np.testing.assert_array_equal(flat_i, nar_i)
    np.testing.assert_array_equal(flat_i[0], xi.sum(axis=0))
    assert nar_i.dtype == np.int32          # widened back after the wire


def test_axis_index_flat_is_linear_rank():
    mesh = make_hybrid_mesh(8, num_slices=2)

    def body(v):
        return v + axis_index_flat(HYBRID_AXES)

    f = shard_map_compat(body, mesh=mesh, in_specs=(P(HYBRID_AXES),),
                         out_specs=P(HYBRID_AXES), check_vma=False)
    got = np.asarray(jax.jit(f)(jnp.zeros(8, jnp.int32)))
    np.testing.assert_array_equal(got, np.arange(8))


# -------------------------------------------------------- planner model


def test_plan_collectives_elects_hierarchical_on_slow_dcn():
    plan = plan_collectives(features=28, num_bins=64, rows_global=10**6,
                            num_slices=2, devices_per_slice=4,
                            ici_gbps=100.0, dcn_gbps=5.0)
    assert plan.hierarchical and plan.elected == "hierarchical"
    assert plan.dcn_bytes == plan.payload_bytes       # pre-aggregated once
    assert plan.flat_dcn_bytes == plan.payload_bytes * 4
    s = plan.summary()
    assert s["mesh_shape"] == [2, 4] and s["hierarchy_elected"]


def test_plan_collectives_flat_cases(monkeypatch):
    # single tier: nothing to elect
    p1 = plan_collectives(features=28, num_bins=64, rows_global=1000,
                          num_slices=1, devices_per_slice=8)
    assert not p1.hierarchical and p1.dcn_bytes == 0
    # forced flat on a hybrid mesh
    monkeypatch.setenv("LGBM_TPU_HIER_REDUCE", "0")
    p2 = plan_collectives(features=28, num_bins=64, rows_global=1000,
                          num_slices=2, devices_per_slice=4)
    assert not p2.hierarchical and p2.elected == "flat"
    assert p2.dcn_bytes == p2.flat_dcn_bytes


def test_plan_collectives_voting_shrinks_dcn():
    kw = dict(features=28, num_bins=64, rows_global=10**6, num_slices=2,
              devices_per_slice=4, ici_gbps=100.0, dcn_gbps=5.0)
    data = plan_collectives(**kw)
    vote = plan_collectives(voting_k=8, **kw)
    assert vote.elected == "hierarchical+voting"
    assert vote.dcn_bytes < data.dcn_bytes       # the acceptance signal
    assert vote.ici_bytes == data.ici_bytes      # full hist still on ICI
    # quantized payloads narrow the wire on BOTH tiers
    quant = plan_collectives(quant=True, quant_bins=16, **kw)
    assert quant.payload_bytes < data.payload_bytes


# ------------------------------------------- end-to-end model parity


@pytest.mark.parametrize("slices", [2, 4])
def test_quant_hierarchical_equals_flat_byte_identical(monkeypatch, slices):
    """Integer histograms are associative, so the tiered schedule must
    change NOTHING: flat single-tier == hierarchical {2x4, 4x2}, byte
    for byte, without pinning."""
    flat, _ = _train(monkeypatch, slices=0, extra=QUANT)
    hier, bst = _train(monkeypatch, slices=slices, hier=True, extra=QUANT)
    assert bst.boosting.collective_plan is not None
    assert bst.boosting.collective_plan.hierarchical
    assert hier == flat
    # and forcing the flat schedule on the SAME hybrid mesh agrees too
    hier_off, _ = _train(monkeypatch, slices=slices, hier=False,
                         extra=QUANT)
    assert hier_off == flat


@pytest.mark.parametrize("slices", [2, 4])
def test_f32_pinned_hier_equals_flat_model_text(monkeypatch, slices):
    """f32 sums are not associative; the pinned tier-ordered reduction
    (all_gather + fixed-order sum per tier) IS the pinned order under
    which hierarchical == flat extends to f32 model text."""
    a, bst = _train(monkeypatch, slices=slices, hier=True, pinned=True)
    b, _ = _train(monkeypatch, slices=slices, hier=False, pinned=True)
    assert bst.boosting.collective_plan.pinned
    assert a == b


def test_voting_hybrid_trains_and_shrinks_dcn(monkeypatch):
    text, bst = _train(monkeypatch, slices=2,
                       extra={"tree_learner": "voting", "top_k": 6})
    plan = bst.boosting.collective_plan
    assert plan is not None and plan.voting_k == 6
    assert plan.elected == "hierarchical+voting"
    assert plan.dcn_bytes < plan.payload_bytes
    p = bst.predict(XV)
    assert np.isfinite(p).all()
    # obs satellites: the two-hop ladder's per-tier payload gauges
    from lightgbm_tpu.obs.metrics import global_registry
    gauges = global_registry.to_dict()["gauges"]
    assert int(gauges["train_ici_payload_bytes"]) == plan.ici_bytes
    assert int(gauges["train_dcn_payload_bytes"]) == plan.dcn_bytes


def test_collective_reduce_spans_show_two_hop_ladder(monkeypatch):
    """A traced hierarchical run's trace shows one collective.reduce
    span per tier (docs/OBSERVABILITY.md) — the two-hop ladder."""
    from lightgbm_tpu.obs.trace import global_tracer
    global_tracer.reset()
    global_tracer.enable()
    try:
        _train(monkeypatch, slices=2, hier=True, rounds=2)
        events = global_tracer.events()
    finally:
        global_tracer.disable()
        global_tracer.reset()
    tiers = {e.get("args", {}).get("tier") for e in events
             if e.get("name") == "collective.reduce"}
    assert DCN_AXIS in tiers and ICI_AXIS in tiers


# ------------------------------------------------------ elastic resume


def test_membership_probe_commits_and_detects_loss():
    world = 4
    fake = make_fake_allgather(world, timeout=2.0)

    def run(chaos):
        out, errs = [None] * world, [None] * world

        def runner(k):
            try:
                ag = fake(k)
                if chaos is not None:
                    ag = chaos.wrap_allgather(ag, k)
                out[k] = membership_probe(
                    ag, world=world, rank=k,
                    config=ResilienceConfig(deadline_s=3.0, max_retries=3,
                                            base_backoff_s=0.01))
            except Exception as e:      # noqa: BLE001 — asserted below
                errs[k] = e
        ts = [threading.Thread(target=runner, args=(k,))
              for k in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not any(t.is_alive() for t in ts), "a rank is HUNG"
        return out, errs

    out, errs = run(None)
    assert errs == [None] * world
    assert all(o == [0, 1, 2, 3] for o in out)

    # seeded chaos kills rank 2's transport for good: every SURVIVOR
    # sees a rank-consistent SliceLostError instead of a hang
    dead = ",".join(f"allgather.stall@{i}:rank=2:sec=60" for i in range(40))
    fake = make_fake_allgather(world, timeout=0.4)
    out, errs = run(ChaosRegistry(dead, seed=3))
    assert all(isinstance(e, SliceLostError) for k, e in enumerate(errs)
               if k != 2), errs


def test_plan_shrunk_world():
    plan = plan_shrunk_world(4, 2, lost_slices=2)
    assert (plan.num_slices, plan.devices_per_slice, plan.total_shards) \
        == (2, 2, 4)
    assert plan.source == "elastic"
    with pytest.raises(SliceLostError):
        plan_shrunk_world(2, 4, lost_slices=2)


def test_elastic_shrink_resume_end_to_end(monkeypatch, tmp_path):
    """The full rejoin: 4x2 world trains with snapshots; a slice loss is
    detected (chaos-killed membership probe); the survivors re-plan a
    2x2 world and resume from the latest VERIFIED bundle — the model
    stays valid, eval history survives, and the new bundle's manifest
    records the re-planned (re-tiled) per-shard plan.

    stochastic_rounding is OFF: each shard folds its axis index into the
    rounding key (i.i.d. noise across shards), so stochastic quant is
    deliberately world-size-DEPENDENT; deterministic quant is the mode
    whose trees are mesh-invariant, which the byte-parity coda needs."""
    monkeypatch.setenv("LGBM_TPU_NUM_SLICES", "4")
    monkeypatch.setenv("LGBM_TPU_SLICE_DEVICES", "2")
    params = dict(BASE, stochastic_rounding=False, **QUANT)
    out = str(tmp_path / "model.txt")
    ev1 = {}
    ds = lgb.Dataset(X, label=Y, free_raw_data=False)
    dv = lgb.Dataset(XV, label=YV, reference=ds, free_raw_data=False)
    bst1 = lgb.train(params, ds, num_boost_round=6, valid_sets=[dv],
                     valid_names=["v"], snapshot_freq=2, snapshot_out=out,
                     verbose_eval=False,
                     callbacks=[lgb.record_evaluation(ev1)])
    assert bst1.boosting.collective_plan.summary()["mesh_shape"] == [4, 2]
    ckdir = out + ".ckpt"

    # ---- "mid-training" slice loss: rank 1's transport dies; the
    # membership probe's rank-consistent verdict IS the shrink decision
    world = 4
    dead = ",".join(f"allgather.stall@{i}:rank=1:sec=60" for i in range(40))
    chaos = ChaosRegistry(dead, seed=11)
    fake = make_fake_allgather(world, timeout=0.4)
    errs = [None] * world

    def runner(k):
        try:
            membership_probe(
                chaos.wrap_allgather(fake(k), k), world=world, rank=k,
                config=ResilienceConfig(deadline_s=3.0, max_retries=3,
                                        base_backoff_s=0.01))
        except Exception as e:          # noqa: BLE001 — asserted below
            errs[k] = e
    ts = [threading.Thread(target=runner, args=(k,)) for k in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert isinstance(errs[0], SliceLostError)

    # ---- shrink + resume on the 2-slice survivor world
    ev2 = {}
    bst2 = shrink_and_resume(
        params, lgb.Dataset(X, label=Y, free_raw_data=False), ckdir,
        num_slices=4, devices_per_slice=2, lost_slices=2,
        num_boost_round=10,
        valid_sets=[lgb.Dataset(XV, label=YV, free_raw_data=False)],
        valid_names=["v"], snapshot_freq=2, snapshot_out=out,
        verbose_eval=False, callbacks=[lgb.record_evaluation(ev2)])
    assert bst2.current_iteration() == 10
    assert bst2.boosting.collective_plan.summary()["mesh_shape"] == [2, 2]
    p = bst2.predict(XV)
    assert np.isfinite(p).all()
    # eval history survives the shrink: the restored prefix is the old
    # world's, byte-equal in quantized mode (hier==flat==any mesh)
    h1 = ev1["v"]["binary_logloss"]
    h2 = ev2["v"]["binary_logloss"]
    assert len(h2) == 10 and h2[:6] == h1
    # the new bundle's manifest records the re-planned per-shard world
    from lightgbm_tpu.resilience.checkpoint import CheckpointManager
    ck = CheckpointManager(ckdir).latest_verified()
    assert ck.iteration == 10
    assert ck.manifest["collective_plan"]["mesh_shape"] == [2, 2]
    assert ck.manifest["hist_plan"] is not None
    # quant mode: the shrunk-world continuation is byte-identical to
    # training 10 rounds on the small world from scratch (re-tiling is
    # exact and integer reductions are mesh-invariant)
    monkeypatch.setenv("LGBM_TPU_NUM_SLICES", "2")
    ds3 = lgb.Dataset(X, label=Y, free_raw_data=False)
    bst3 = lgb.train(params, ds3, num_boost_round=10, verbose_eval=False)
    assert bst2.model_to_string() == bst3.model_to_string()


# ------------------------------------------------------------- probe


def test_collective_probe_json():
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    from collective_probe import run_probe
    out = run_probe(rows=4096, features=8, max_bin=31, trees=10,
                    num_slices=2, top_k=4, reps=1)
    assert out["mesh_shape"] == [2, 4]
    for payload in ("f32", "quant"):
        sec = out[payload]
        assert sec["voting_dcn_below_data"]
        assert sec["voting_parallel"]["dcn_bytes"] \
            < sec["data_parallel"]["dcn_bytes"]
        assert sec["data_parallel"]["dcn_bytes_total"] > 0
    assert out["quant"]["payload_bytes"] < out["f32"]["payload_bytes"]
    assert {"hierarchy_elected", "ici_bytes", "dcn_bytes",
            "voting_k"} <= out.keys()
    json.dumps(out)                      # journal-able


# ------------------------------------------------------------- stress


@pytest.mark.slow
def test_two_slice_stress_voting_quant(monkeypatch):
    """2-slice stress on a larger workload: the quantized DATA learner
    stays byte-identical across the flat and hierarchical schedules, and
    the hierarchical VOTING learner — per-SLICE election is a genuinely
    different (DCN-cheaper) schedule, so no byte parity is claimed for
    it — still trains a usable model with the DCN payload shrunk."""
    rng = np.random.RandomState(3)
    n = 20_000
    Xl = rng.randn(n, 24).astype(np.float32)
    yl = (Xl[:, 0] * Xl[:, 1] + Xl[:, 2] + 0.1 * rng.randn(n) > 0).astype(
        np.float32)

    def run(learner, hier, extra=None):
        monkeypatch.setenv("LGBM_TPU_NUM_SLICES", "2")
        monkeypatch.setenv("LGBM_TPU_HIER_REDUCE", "1" if hier else "0")
        params = dict(BASE, tree_learner=learner, num_leaves=31,
                      **QUANT, **(extra or {}))
        ds = lgb.Dataset(Xl, label=yl, free_raw_data=False)
        return lgb.train(params, ds, num_boost_round=20,
                         verbose_eval=False)

    a = run("data", True)
    b = run("data", False)
    assert a.model_to_string() == b.model_to_string()
    v = run("voting", True, {"top_k": 8})
    plan = v.boosting.collective_plan
    assert plan.elected == "hierarchical+voting"
    assert plan.dcn_bytes < plan.payload_bytes
    pred = v.predict(Xl[:2000])
    acc = np.mean((pred > 0.5) == (yl[:2000] > 0.5))
    assert acc > 0.7
