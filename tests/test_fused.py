"""Fused Pallas histogram→split megakernel (ops/fused.py) parity suite.

The contract (docs/PERF.md "fused megakernel"):

- QUANT/INT paths: per-feature-best tuples (gain/bin/direction/left
  sums) BIT-IDENTICAL to the staged ``build_histogram_int`` /
  ``segment_histogram_int`` + ``quant_rescale_hist`` +
  ``feature_best_splits`` pipeline, across tile/block sizes (incl. a
  ragged last tile) and sibling-subtraction children — integer
  accumulation is associative and the scan body is SHARED
  (``ops.split.numeric_feature_scan``), so equality is exact.
- F32 paths: the fused histogram matches the staged one to f32
  accumulation order (allclose), and the in-kernel scan is bit-identical
  to the shared scan applied to the fused kernel's own histograms —
  pinning the kernel's epilogue exactly; end-to-end the grower produces
  structurally identical trees and the quantized engine run is
  model-text-identical.
- ``hist_method=auto`` elects fused only when the planner proves the
  VMEM arena fits; the staged family is the fallback arm.

Everything here runs in ``interpret=True`` on the tier-1 CPU run; the
``pallas``-marked stress test exercises the compiled kernel on
accelerators.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.dataset import FeatureMeta
from lightgbm_tpu.grower import GrowerConfig, grow_tree
from lightgbm_tpu.grower_rounds import grow_tree_rounds
from lightgbm_tpu.ops import fused as FU
from lightgbm_tpu.ops import histogram as H
from lightgbm_tpu.ops.split import (SplitHyperparams, feature_best_splits,
                                    numeric_feature_scan, quant_rescale_hist)

pytestmark = pytest.mark.pallas


def _meta(B, F):
    return FeatureMeta(
        num_bin=np.full(F, B, np.int32),
        missing_type=np.zeros(F, np.int32),
        default_bin=np.zeros(F, np.int32),
        most_freq_bin=np.zeros(F, np.int32),
        is_categorical=np.zeros(F, bool),
        max_num_bin=B,
    )


def _data(seed=0, n=3000, F=7, B=32, K=4):
    rng = np.random.RandomState(seed)
    binned = jnp.asarray(rng.randint(0, B - 1, (F, n)), jnp.uint8)
    g = jnp.asarray(rng.randn(n), jnp.float32)
    h = jnp.abs(g) + 0.1
    w = jnp.asarray((rng.rand(n) > 0.3).astype(np.float32) * 1.5)
    slot = jnp.asarray(
        np.where(rng.rand(n) < 0.8, rng.randint(0, K, n), K), jnp.int32)
    return binned, g, h, w, slot


def _slot_sums(seg_ref):
    """Per-slot totals from the staged reference hist (channel sums of
    any one feature's bins — here summed over all features / F)."""
    return jnp.stack([seg_ref[:, c].sum((-1, -2)) / seg_ref.shape[-2]
                      for c in range(3)])


_HP = SplitHyperparams(min_data_in_leaf=5)
# tile/block sizes: ragged last tile (3000 % 512 != 0), minimum block,
# and a feature tile that does not divide F
_SHAPES = [(None, None), (4, 128), (3, 256), (8, 512), (1, 128)]


@pytest.mark.parametrize("feat_tile,block_rows", _SHAPES)
def test_fused_quant_bit_identical(feat_tile, block_rows):
    """Quant leaf mode: hist AND per-feature-best tuples bit-identical
    to the staged pipeline for every arena/tile decomposition."""
    n, F, B, K = 3000, 7, 32, 4
    binned, g, h, w, slot = _data(n=n, F=F, B=B, K=K)
    member = w > 0
    gq, hq, gs, hs = H.quantize_gradients(g, h, w, 8, jax.random.PRNGKey(0))
    slot_w = jnp.where(member, slot, K)
    seg_i = H.segment_histogram_int(binned, gq, hq, member, slot, K, B,
                                    levels=H.quant_levels(8))
    seg_f = H.segment_histogram(binned, g, h, w, slot, K, B)
    sums = _slot_sums(seg_f)
    nb = jnp.full((F,), B, jnp.int32)
    zz = jnp.zeros((F,), jnp.int32)
    fh, fb = FU.fused_segment_splits(
        binned, H._vals_t_int(gq, hq, member), slot_w, K, B, sums,
        nb, zz, zz, _HP, quant_scales=(gs, hs),
        feat_tile=feat_tile, block_rows=block_rows)
    assert np.array_equal(np.asarray(fh), np.asarray(seg_i))
    for k in range(K):
        h3 = quant_rescale_hist(seg_i[k], gs, hs, sums[2][k])
        ref = numeric_feature_scan(h3, sums[0][k], sums[1][k], sums[2][k],
                                   nb, zz, zz, _HP)
        for name in ref._fields:
            assert np.array_equal(np.asarray(getattr(fb, name))[k],
                                  np.asarray(getattr(ref, name))), \
                (name, k, feat_tile, block_rows)


@pytest.mark.parametrize("feat_tile,block_rows", [(None, None), (3, 128)])
def test_fused_f32_hist_and_scan_parity(feat_tile, block_rows):
    """F32 leaf mode: fused hist tracks the staged scatter hist to f32
    accumulation order; the in-kernel scan is BIT-identical to the
    shared scan run on the fused kernel's own histograms."""
    n, F, B, K = 3000, 7, 32, 4
    binned, g, h, w, slot = _data(n=n, F=F, B=B, K=K)
    seg_ref = H.segment_histogram(binned, g, h, w, slot, K, B)
    sums = _slot_sums(seg_ref)
    nb = jnp.full((F,), B, jnp.int32)
    zz = jnp.zeros((F,), jnp.int32)
    fh, fb = FU.fused_segment_splits(
        binned, H._vals_t(g, h, w), slot, K, B, sums, nb, zz, zz, _HP,
        feat_tile=feat_tile, block_rows=block_rows)
    np.testing.assert_allclose(np.asarray(fh), np.asarray(seg_ref),
                               rtol=1e-5, atol=2e-3)
    ref = numeric_feature_scan(fh, sums[0], sums[1], sums[2],
                               nb, zz, zz, _HP)
    for name in ref._fields:
        assert np.array_equal(np.asarray(getattr(fb, name)),
                              np.asarray(getattr(ref, name))), name
    # and the tuples agree with the STAGED scan to f32 tolerance
    staged = numeric_feature_scan(seg_ref, sums[0], sums[1], sums[2],
                                  nb, zz, zz, _HP)
    sg, fg = np.asarray(staged.gain), np.asarray(fb.gain)
    finite = np.isfinite(sg) & np.isfinite(fg)
    assert (np.isfinite(sg) == np.isfinite(fg)).all()
    np.testing.assert_allclose(fg[finite], sg[finite], rtol=1e-4)


def test_fused_frontier_sibling_derivation():
    """Parent mode: the in-kernel ``sibling = parent − smaller``
    derivation + both-children scan must equal the staged subtraction
    pipeline — bit-identical in quant, scan-exact in f32."""
    n, F, B, K = 2000, 5, 16, 3
    binned, g, h, w, slot = _data(seed=2, n=n, F=F, B=B, K=K)
    member = w > 0
    gq, hq, gs, hs = H.quantize_gradients(g, h, w, 8, jax.random.PRNGKey(1))
    slot_w = jnp.where(member, slot, K)
    small = H.segment_histogram_int(binned, gq, hq, member, slot_w, K, B,
                                    levels=H.quant_levels(8))
    rng = np.random.RandomState(3)
    # REAL parents: the small child's rows plus extra rows drawn from the
    # currently-dropped lanes, slotted the same way (a genuine histogram
    # — every feature's bins partition the same parent rows, which is
    # what the kernel's per-block count factor relies on)
    extra_slot = jnp.asarray(
        np.where((np.asarray(slot_w) == K) & (rng.rand(n) < 0.5),
                 rng.randint(0, K, n), K), jnp.int32)
    slot_parent = jnp.where(slot_w < K, slot_w, extra_slot)
    parent = H.segment_histogram_int(binned, gq, hq, member, slot_parent,
                                     K, B, levels=H.quant_levels(8))
    small_left = jnp.asarray([True, False, True])
    h_left = jnp.where(small_left[:, None, None, None], small,
                       parent - small)
    h_right = parent - h_left
    nb = jnp.full((F,), B, jnp.int32)
    zz = jnp.zeros((F,), jnp.int32)
    # per-child totals consistent with the child histograms (sums from
    # the integer hists rescaled; counts = member-row counts)
    children = jnp.concatenate([h_left, h_right])
    csums = jnp.stack([
        children[:, 0].sum((-1, -2)).astype(jnp.float32) / F * gs,
        children[:, 1].sum((-1, -2)).astype(jnp.float32) / F * hs,
        children[:, 1, 0, :].sum(-1).astype(jnp.float32)])
    fh, fb = FU.fused_frontier_splits(
        binned, H._vals_t_int(gq, hq, member), slot_w, K, B, csums,
        small_left, parent, nb, zz, zz, _HP, quant_scales=(gs, hs),
        feat_tile=2, block_rows=128)
    assert np.array_equal(np.asarray(fh), np.asarray(small))
    for c in range(2 * K):
        h3 = quant_rescale_hist(children[c], gs, hs, csums[2][c])
        ref = numeric_feature_scan(h3, csums[0][c], csums[1][c],
                                   csums[2][c], nb, zz, zz, _HP)
        for name in ref._fields:
            assert np.array_equal(np.asarray(getattr(fb, name))[c],
                                  np.asarray(getattr(ref, name))), (name, c)


@pytest.mark.parametrize("grower", ["serial", "rounds"])
def test_fused_grower_quant_bit_identical_trees(grower):
    """Both growers' fused arm must produce BIT-identical TreeArrays to
    the staged arm in quantized mode (integer hists + shared scan)."""
    rng = np.random.RandomState(1)
    n, F, B = 4000, 6, 32
    binned = rng.randint(0, B - 1, (n, F)).astype(np.uint8)
    y = np.sin(binned[:, 0] * 0.3) + 0.2 * binned[:, 1] + rng.randn(n) * 0.1
    grad = (-y).astype(np.float32)
    hess = np.ones(n, np.float32)
    mask = np.ones(n, np.float32)
    meta = _meta(B, F)
    gq, hq, gs, hs = H.quantize_gradients(
        jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(mask), 8,
        jax.random.PRNGKey(7))
    cfg = GrowerConfig(num_leaves=15, hp=SplitHyperparams(min_data_in_leaf=5),
                       num_bins=B, round_width=8, quant=True, quant_bins=8)
    fn = grow_tree if grower == "serial" else grow_tree_rounds
    args = (jnp.asarray(binned.T), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask), meta)
    t_st, lid_st = fn(*args, cfg, quant_vals=(gq, hq, gs, hs))
    t_fu, lid_fu = fn(*args, cfg._replace(hist_method="fused",
                                          fused_feat_tile=3,
                                          fused_block_rows=128),
                      quant_vals=(gq, hq, gs, hs))
    assert int(t_fu.num_leaves) == 15
    for name in t_st._fields:
        assert np.array_equal(np.asarray(getattr(t_st, name)),
                              np.asarray(getattr(t_fu, name))), name
    assert np.array_equal(np.asarray(lid_st), np.asarray(lid_fu))


@pytest.mark.parametrize("tile", [0, 256])
@pytest.mark.parametrize("grower", ["serial", "rounds"])
def test_fused_grower_f32_structurally_identical(grower, tile):
    """F32 fused arm, untiled AND under planner row tiling (the tile
    caps the kernel's DMA block, refining the f32 dot partition): same
    splits/structure as staged (floats may differ in the last bits —
    different accumulation order, the CPU-vs-GPU class of difference;
    this is why the f32 fused row is absent from test_macro's
    byte-identical tiled==untiled matrix, where only fused_quant rides)."""
    rng = np.random.RandomState(4)
    n, F, B = 4000, 6, 32
    binned = rng.randint(0, B - 1, (n, F)).astype(np.uint8)
    y = np.sin(binned[:, 0] * 0.3) + 0.2 * binned[:, 1] + rng.randn(n) * 0.1
    grad = (-y).astype(np.float32)
    hess = np.ones(n, np.float32)
    mask = np.ones(n, np.float32)
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=15, hp=SplitHyperparams(min_data_in_leaf=5),
                       num_bins=B, round_width=8)
    fn = grow_tree if grower == "serial" else grow_tree_rounds
    args = (jnp.asarray(binned.T), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask), meta)
    t_st, lid_st = fn(*args, cfg._replace(tile_rows=tile))
    t_fu, lid_fu = fn(*args, cfg._replace(hist_method="fused",
                                          fused_feat_tile=3,
                                          fused_block_rows=128,
                                          tile_rows=tile))
    for name in ("split_feature", "threshold_bin", "default_left",
                 "left_child", "right_child", "num_leaves"):
        assert np.array_equal(np.asarray(getattr(t_st, name)),
                              np.asarray(getattr(t_fu, name))), name
    assert np.array_equal(np.asarray(lid_st), np.asarray(lid_fu))
    np.testing.assert_allclose(np.asarray(t_fu.leaf_value),
                               np.asarray(t_st.leaf_value),
                               rtol=3e-5, atol=1e-7)


def _strip_param_lines(text):
    return "\n".join(ln for ln in text.splitlines()
                     if not ln.startswith("[tpu_hist_method"))


def test_fused_engine_quant_model_text_identical():
    """End-to-end ``lgb.train``: quantized fused == staged model text
    (modulo the echoed tpu_hist_method parameter line)."""
    rng = np.random.RandomState(3)
    X = rng.randn(3000, 8).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + rng.randn(3000) * 0.1 > 0.3
         ).astype(np.float32)
    texts = {}
    for method in ("auto", "fused"):
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train(
            dict(objective="binary", num_leaves=15, min_data_in_leaf=5,
                 verbose=-1, tpu_hist_method=method,
                 use_quantized_grad=True, num_grad_quant_bins=8),
            ds, num_boost_round=5)
        texts[method] = _strip_param_lines(bst.model_to_string())
    assert texts["auto"] == texts["fused"]


def test_fused_engine_f32_predictions_close():
    rng = np.random.RandomState(6)
    X = rng.randn(2500, 8).astype(np.float32)
    y = (X[:, 0] - 0.3 * X[:, 2] + rng.randn(2500) * 0.1).astype(np.float32)
    preds = {}
    for method in ("auto", "fused"):
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train(
            dict(objective="regression", num_leaves=15, verbose=-1,
                 tpu_hist_method=method), ds, num_boost_round=5)
        preds[method] = bst.predict(X[:400])
    np.testing.assert_allclose(preds["fused"], preds["auto"],
                               rtol=1e-4, atol=1e-6)


def test_fused_gate_lifted_monotone_and_categorical():
    """Monotone constraints and categorical features now RIDE the fused
    arm (monotone bounds thread into the in-kernel scan; per-category
    stats are the same segment reduction + pick_fused_best's cat merge)
    — the grower config must KEEP hist_method=fused and still train.
    Contexts genuinely outside the arm (extra_trees' per-node
    randomness) still warn/fall back."""
    rng = np.random.RandomState(8)
    X = rng.randn(800, 5).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.Booster(
        params=dict(objective="binary", num_leaves=7, verbosity=-1,
                    tpu_hist_method="fused",
                    monotone_constraints=[1, 0, 0, 0, 0]),
        train_set=ds)
    assert bst.boosting.grower_cfg.hist_method == "fused"
    for _ in range(3):
        bst.update()
    assert bst.num_trees() == 3
    # categorical rides the fused arm under the rounds grower
    Xc = np.column_stack([rng.randint(0, 6, 800), X[:, 1:]]).astype(
        np.float32)
    ds2 = lgb.Dataset(Xc, label=y, categorical_feature=[0],
                      free_raw_data=False)
    bst2 = lgb.Booster(
        params=dict(objective="binary", num_leaves=7, verbosity=-1,
                    tpu_hist_method="fused", tpu_tree_growth="rounds"),
        train_set=ds2)
    assert bst2.boosting.grower_cfg.hist_method == "fused"
    for _ in range(3):
        bst2.update()
    assert bst2.num_trees() == 3
    # the SERIAL grower keeps its narrower gate for categorical
    bst3 = lgb.Booster(
        params=dict(objective="binary", num_leaves=7, verbosity=-1,
                    tpu_hist_method="fused", tpu_tree_growth="serial"),
        train_set=lgb.Dataset(Xc, label=y, categorical_feature=[0],
                              free_raw_data=False))
    assert bst3.boosting.grower_cfg.hist_method != "fused"
    # extra_trees stays a genuine fallback (per-node randomized bins)
    bst4 = lgb.Booster(
        params=dict(objective="binary", num_leaves=7, verbosity=-1,
                    tpu_hist_method="fused", extra_trees=True),
        train_set=lgb.Dataset(X, label=y, free_raw_data=False))
    assert bst4.boosting.grower_cfg.hist_method != "fused"
    for _ in range(2):
        bst4.update()
    assert bst4.num_trees() == 2


def test_fused_auto_elects_on_accelerator(monkeypatch):
    """Regression: hist_method=auto must reach the planner's fused
    election AS "auto" on accelerators — the measured-kernel probe
    resolving auto to a concrete name first would make the election
    unreachable (the planner only elects for method in {auto, fused})."""
    import lightgbm_tpu.boosting.gbdt as G
    monkeypatch.setattr(G, "on_accelerator", lambda: True)
    # fused_kernel_verified consults ops.fused.on_accelerator (not
    # patched): CPU -> trivially verified, no accelerator probe runs;
    # measured_best_method likewise short-circuits off-accelerator if
    # the election ever declined to it
    rng = np.random.RandomState(12)
    Xa = rng.randn(1500, 6).astype(np.float32)
    ya = (Xa[:, 0] > 0).astype(np.float32)
    ds = lgb.Dataset(Xa, label=ya, free_raw_data=False)
    bst = lgb.Booster(params=dict(objective="binary", num_leaves=7,
                                  verbosity=-1, tpu_hist_method="auto"),
                      train_set=ds)
    plan = bst.boosting.hist_plan
    assert plan.fused, plan.summary()
    assert bst.boosting.grower_cfg.hist_method == "fused"
    assert bst.boosting.grower_cfg.fused_feat_tile == plan.fused_feat_tile


def test_fused_env_gate(monkeypatch):
    """LGBM_TPU_FUSED=0 drops the fused arm: the planner must never
    elect it and explicit hist_method=fused degrades to staged."""
    from lightgbm_tpu.ops.planner import plan_histograms
    monkeypatch.setenv("LGBM_TPU_FUSED", "0")
    plan = plan_histograms(10_000, 8, 64, method="fused", round_width=8,
                           fused_ok=True)
    assert not plan.fused and plan.variant != "fused"
    monkeypatch.delenv("LGBM_TPU_FUSED")
    plan = plan_histograms(10_000, 8, 64, method="fused", round_width=8,
                           fused_ok=True)
    assert plan.fused and plan.variant == "fused"
    assert plan.fused_feat_tile > 0 and plan.fused_block_rows >= 128


def test_fused_planner_vmem_election():
    """plan_fused: fits at sane shapes, degrades feat_tile under a tight
    fake VMEM budget, refuses when nothing fits (auto then keeps the
    staged family)."""
    from lightgbm_tpu.ops.planner import (fused_vmem_bytes, plan_fused,
                                          plan_histograms)
    fp = plan_fused(128, 256, quant=True)
    assert fp is not None
    # monotone in feat_tile
    assert fused_vmem_bytes(128, 256, 8, 512, True) > \
        fused_vmem_bytes(128, 256, 1, 128, True)
    # a 256 KiB budget fits nothing at frontier width 128
    assert plan_fused(128, 256, quant=False, vmem_bytes=256 << 10) is None
    plan = plan_histograms(100_000, 28, 256, method="auto", round_width=128,
                           fused_ok=True, vmem_bytes=256 << 10)
    assert not plan.fused
    assert plan.variant != "fused"
    # the same shape with the real default budget elects fused
    plan2 = plan_histograms(100_000, 28, 256, method="auto",
                            round_width=128, fused_ok=True)
    assert plan2.fused and plan2.variant == "fused"
    assert plan2.fused_vmem_bytes <= plan2.vmem_limit_bytes


def test_fused_apply_plan_threading():
    """apply_plan flips hist_method to fused (with kernel shape) when
    elected, and degrades an explicit fused that cannot fit."""
    from lightgbm_tpu.ops.planner import apply_plan
    cfg = GrowerConfig(num_leaves=15, num_bins=64, round_width=8,
                       hist_method="auto")
    cfg2, plan = apply_plan(cfg, 10_000, 8, fused_ok=True)
    assert plan.fused and cfg2.hist_method == "fused"
    assert cfg2.fused_feat_tile == plan.fused_feat_tile > 0
    cfg3, plan3 = apply_plan(cfg._replace(hist_method="fused"), 10_000, 8,
                             fused_ok=False)
    assert not plan3.fused and cfg3.hist_method == "auto"


def test_fused_sharded_grower_data_keeps_feature_downgrades():
    """DATA sharding now KEEPS hist_method=fused (the rounds grower
    splits the kernel at the collective seam, grower_rounds.py); only
    FEATURE sharding resolves fused to the staged family (the winner
    exchange moves SplitResults, not histograms).  The payload
    accounting helpers stay in lockstep with the writeback."""
    from lightgbm_tpu.parallel.learners import fused_best_payload_bytes
    assert fused_best_payload_bytes(28) == 6 * 28 * 4
    assert FU.hist_scan_traffic_bytes(8, 28, 64) == 8 * 3 * 28 * 64 * 4 * 4
    assert FU.hist_scan_traffic_bytes(8, 28, 64, quant=True) == \
        8 * 2 * 28 * 64 * 4 * 4
    if jax.device_count() >= 2:
        from lightgbm_tpu.parallel.learners import (make_mesh,
                                                    make_sharded_grower,
                                                    shard_dataset)
        rng = np.random.RandomState(0)
        n, F, B = 2048, 5, 16
        binned = rng.randint(0, B - 1, (n, F)).astype(np.uint8)
        g = rng.randn(n).astype(np.float32)
        mesh = make_mesh(2)
        cfg = GrowerConfig(num_leaves=7, num_bins=B,
                           hp=SplitHyperparams(min_data_in_leaf=5),
                           hist_method="fused")
        grower = make_sharded_grower(mesh, _meta(B, F), cfg)
        (bt, gg, hh, mm), _ = shard_dataset(
            mesh, binned, g, np.ones(n, np.float32),
            np.ones(n, np.float32))
        tree, leaf_id = grower(bt, gg, hh, mm)
        assert int(tree.num_leaves) >= 2


def test_fused_seam_halves_equal_combined():
    """The collective seam (grower_rounds.py's sharded arm): accumulate
    → identity reduce → standalone sibling-derive+scan must reproduce
    the single-program ``fused_frontier_splits`` exactly — quant
    BIT-identical (hist and every best-tuple field), f32 scan-exact —
    with monotone constraints and child bounds threaded through both."""
    n, F, B, K = 2500, 6, 16, 3
    binned, g, h, w, slot = _data(seed=5, n=n, F=F, B=B, K=K)
    member = w > 0
    nb = jnp.full((F,), B, jnp.int32)
    zz = jnp.zeros((F,), jnp.int32)
    mono = jnp.asarray([1, -1, 0, 0, 1, 0], jnp.int32)
    NC = 2 * K
    bounds = (jnp.full((NC,), -4.0, jnp.float32),
              jnp.full((NC,), 4.0, jnp.float32))
    small_left = jnp.asarray([True, False, True])

    # quant: parent = small-child rows plus extra rows (a real histogram)
    gq, hq, gs, hs = H.quantize_gradients(g, h, w, 8, jax.random.PRNGKey(3))
    slot_w = jnp.where(member, slot, K)
    rng = np.random.RandomState(6)
    extra = jnp.asarray(
        np.where((np.asarray(slot_w) == K) & (rng.rand(n) < 0.5),
                 rng.randint(0, K, n), K), jnp.int32)
    slot_parent = jnp.where(slot_w < K, slot_w, extra)
    parent = H.segment_histogram_int(binned, gq, hq, member, slot_parent,
                                     K, B, levels=H.quant_levels(8))
    small = H.segment_histogram_int(binned, gq, hq, member, slot_w, K, B,
                                    levels=H.quant_levels(8))
    h_left = jnp.where(small_left[:, None, None, None], small,
                       parent - small)
    children = jnp.concatenate([h_left, parent - h_left])
    csums = jnp.stack([
        children[:, 0].sum((-1, -2)).astype(jnp.float32) / F * gs,
        children[:, 1].sum((-1, -2)).astype(jnp.float32) / F * hs,
        children[:, 1, 0, :].sum(-1).astype(jnp.float32)])
    vals = H._vals_t_int(gq, hq, member)
    fh_c, fb_c = FU.fused_frontier_splits(
        binned, vals, slot_w, K, B, csums, small_left, parent,
        nb, zz, zz, _HP, quant_scales=(gs, hs),
        monotone_constraints=mono, child_bounds=bounds)
    # the seam: local accumulate, (identity) collective, epilogue scan
    from lightgbm_tpu.parallel.collectives import psum_int_tiered
    acc = FU.fused_frontier_accumulate(binned, vals, slot_w, K, B)
    acc = psum_int_tiered(acc, None)          # unsharded degenerate tier
    fb_s = FU.fused_sibling_scan(
        acc, csums, nb, zz, zz, _HP, small_left=small_left,
        parent_hist=parent, quant_scales=(gs, hs),
        monotone_constraints=mono, child_bounds=bounds)
    assert np.array_equal(np.asarray(acc), np.asarray(fh_c))
    assert np.array_equal(np.asarray(acc), np.asarray(small))
    for name in fb_c._fields:
        assert np.array_equal(np.asarray(getattr(fb_s, name)),
                              np.asarray(getattr(fb_c, name))), name

    # f32 twin (same seam, float arena): scan parity is exact because
    # both arms scan the SAME reduced histogram with the shared body
    smallf = H.segment_histogram(binned, g, h, w, slot_w, K, B)
    parentf = H.segment_histogram(binned, g, h, w, slot_parent, K, B)
    h_lf = jnp.where(small_left[:, None, None, None], smallf,
                     parentf - smallf)
    chf = jnp.concatenate([h_lf, parentf - h_lf])
    csf = jnp.stack([chf[:, 0].sum((-1, -2)) / F,
                     chf[:, 1].sum((-1, -2)) / F,
                     chf[:, 2].sum((-1, -2)) / F])
    valsf = H._vals_t(g, h, w)
    fh_cf, fb_cf = FU.fused_frontier_splits(
        binned, valsf, slot_w, K, B, csf, small_left, parentf,
        nb, zz, zz, _HP, monotone_constraints=mono, child_bounds=bounds)
    from lightgbm_tpu.parallel.collectives import psum_tiered
    accf = psum_tiered(FU.fused_frontier_accumulate(
        binned, valsf, slot_w, K, B), None)
    fb_sf = FU.fused_sibling_scan(
        accf, csf, nb, zz, zz, _HP, small_left=small_left,
        parent_hist=parentf, monotone_constraints=mono,
        child_bounds=bounds)
    np.testing.assert_allclose(np.asarray(accf), np.asarray(fh_cf),
                               rtol=1e-5, atol=2e-3)
    sg, fg = np.asarray(fb_cf.gain), np.asarray(fb_sf.gain)
    finite = np.isfinite(sg) & np.isfinite(fg)
    assert (np.isfinite(sg) == np.isfinite(fg)).all()
    np.testing.assert_allclose(fg[finite], sg[finite], rtol=1e-4)


def test_fused_monotone_scan_matches_staged():
    """The lifted monotone gate: the in-kernel scan with constraints +
    child bounds must equal the shared ``numeric_feature_scan`` given
    the same arguments — bit-identical on the kernel's own hists."""
    n, F, B, K = 2000, 5, 16, 3
    binned, g, h, w, slot = _data(seed=7, n=n, F=F, B=B, K=K)
    seg_ref = H.segment_histogram(binned, g, h, w, slot, K, B)
    sums = _slot_sums(seg_ref)
    nb = jnp.full((F,), B, jnp.int32)
    zz = jnp.zeros((F,), jnp.int32)
    mono = jnp.asarray([1, -1, 0, 1, -1], jnp.int32)
    bounds = (jnp.full((K,), -2.0, jnp.float32),
              jnp.full((K,), 2.0, jnp.float32))
    fh, fb = FU.fused_segment_splits(
        binned, H._vals_t(g, h, w), slot, K, B, sums, nb, zz, zz, _HP,
        monotone_constraints=mono, child_bounds=bounds)
    ref = numeric_feature_scan(fh, sums[0], sums[1], sums[2], nb, zz, zz,
                               _HP, monotone_constraints=mono,
                               leaf_output_bounds=bounds)
    for name in ref._fields:
        assert np.array_equal(np.asarray(getattr(fb, name)),
                              np.asarray(getattr(ref, name))), name
    # constraints actually bit: the constrained election must differ
    # from the unconstrained scan somewhere (gain or threshold)
    fb_un = FU.fused_segment_splits(
        binned, H._vals_t(g, h, w), slot, K, B, sums, nb, zz, zz, _HP)[1]
    assert (not np.array_equal(np.asarray(fb_un.gain), np.asarray(fb.gain))
            or not np.array_equal(np.asarray(fb_un.threshold),
                                  np.asarray(fb.threshold)))


def test_fused_probe_json():
    """tools/hist_probe.py --fused column: staged vs fused sec/level +
    accounting fields ride the bench hist_probe stage journal."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from hist_probe import run_probe
    out = run_probe(rows=8000, features=6, max_bin=15, quant_bins=4,
                    leaves=15, reps=1)
    f = out["fused"]
    assert f["hist_scan_traffic_bytes"] > 0
    assert f["best_tuple_payload_bytes"] == 6 * 6 * 4
    assert "staged" in f and "fused" in f
    if "error" not in f["fused"]:
        assert f["fused"]["sec_per_level"] > 0


def test_histogram_pallas_tile_rows_parity():
    """Satellite: the bin-only Pallas kernel under the tile_rows regime —
    capping the block must leave results equal to the scatter reference,
    and the planner now models a "pallas" variant peak."""
    from lightgbm_tpu.ops.planner import predict_peak_bytes
    rng = np.random.RandomState(3)
    n, F, B = 2579, 5, 17
    binned = jnp.asarray(rng.randint(0, B, (F, n)), jnp.uint8)
    g = jnp.asarray(rng.randn(n), jnp.float32)
    h = jnp.asarray(rng.rand(n), jnp.float32)
    m = jnp.asarray((rng.rand(n) < 0.6), jnp.float32)
    ref = np.asarray(H.build_histogram(binned, g, h, m, B, method="scatter"))
    for tile in (192, 7, 4096):
        got = np.asarray(H.build_histogram(binned, g, h, m, B,
                                           method="pallas", tile_rows=tile))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    # peak model: the pallas variant's transient is O(tile), far below
    # the scatter variant's lane-padded update buffer
    pal = predict_peak_bytes(1_000_000, 28, 64, variant="pallas",
                             accel=True)[0]
    sca = predict_peak_bytes(1_000_000, 28, 64, variant="scatter",
                             accel=True)[0]
    assert pal < sca


@pytest.mark.slow
def test_fused_stress_wide_frontier():
    """Accelerator-shaped stress: full round_width=64 frontier, B=64,
    u16-capable shapes — quant bit-parity at scale (interpret mode on
    CPU; the compiled kernel on accelerators via -m 'pallas and slow')."""
    n, F, B, K = 20_000, 12, 64, 64
    binned, g, h, w, slot = _data(seed=9, n=n, F=F, B=B, K=K)
    member = w > 0
    gq, hq, gs, hs = H.quantize_gradients(g, h, w, 16, jax.random.PRNGKey(2))
    slot_w = jnp.where(member, slot, K)
    seg_i = H.segment_histogram_int(binned, gq, hq, member, slot, K, B,
                                    levels=H.quant_levels(16))
    sums = _slot_sums(H.segment_histogram(binned, g, h, w, slot, K, B))
    nb = jnp.full((F,), B, jnp.int32)
    zz = jnp.zeros((F,), jnp.int32)
    fh, fb = FU.fused_segment_splits(
        binned, H._vals_t_int(gq, hq, member), slot_w, K, B, sums,
        nb, zz, zz, _HP, quant_scales=(gs, hs))
    assert np.array_equal(np.asarray(fh), np.asarray(seg_i))
    assert np.isfinite(np.asarray(fb.left_count)).all()
