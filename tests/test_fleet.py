"""Serving fleet (lightgbm_tpu/fleet/): multi-model registry,
planner-driven shared-HBM eviction, AOT cold start, opt-in low-precision
inference (docs/SERVING.md fleet section).

All CPU-runnable under the tier-1 command.  Data is float32-precise so
the "device" backend's routing-exactness domain applies: the default
(f32) fleet path must be BIT-equal to ``Booster.predict(raw_score=True)``
— resident, evicted, and AOT-restored alike.
"""

import json
import os
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.fleet import AOTStore, Fleet, quantize_forest
from lightgbm_tpu.fleet.lowprec import int8_rows, measure_accuracy_delta
from lightgbm_tpu.ops.planner import (HEADROOM, FleetModelShape, plan_fleet,
                                      predict_forest_bytes,
                                      predict_program_bytes)
from lightgbm_tpu.serving import (LowPrecisionQuarantined, ModelNotFound,
                                  QueueFull)

pytestmark = pytest.mark.fleet

F = 10


def _f32_data(rng, n, f=F):
    return rng.randn(n, f).astype(np.float32).astype(np.float64)


def _train(n=1200, rounds=10, leaves=15, seed=0, num_class=None):
    rng = np.random.RandomState(seed)
    X = _f32_data(rng, n)
    params = {"objective": "binary", "verbosity": -1, "num_leaves": leaves}
    if num_class:
        params = {"objective": "multiclass", "num_class": num_class,
                  "verbosity": -1, "num_leaves": leaves}
        y = rng.randint(0, num_class, n).astype(float)
    else:
        y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds, verbose_eval=False)


@pytest.fixture(scope="module")
def boosters():
    return [_train(seed=0), _train(seed=1), _train(seed=2, num_class=3)]


def _fleet3(boosters, **kw):
    kw.setdefault("max_batch_rows", 128)
    fleet = Fleet(**kw)
    # keep the interactive class generous: a first-compile stall on a
    # loaded CI box must not expire legitimate traffic mid-test (the
    # deadline-class mechanics get their own tightened test below)
    fleet.config.deadline_classes["interactive"] = 10_000.0
    fleet.add_model("m0", boosters[0], weight=3.0,
                    deadline_class="interactive")
    fleet.add_model("m1", boosters[1], weight=1.0)
    fleet.add_model("m2", boosters[2], weight=1.0, deadline_class="batch")
    return fleet


def _hot_only_budget(fleet, hot="m0"):
    """A caller budget that fits exactly the hottest model's residency."""
    plan = fleet.replan()
    mp = next(m for m in plan.models if m.name == hot)
    return int((mp.forest_bytes + mp.program_bytes + 1024) / HEADROOM)


# ------------------------------------------------------------- planner


def test_plan_fleet_budget_election():
    shapes = [
        FleetModelShape("hot", 100, 30, 31, F, buckets=(8, 64), weight=4.0),
        FleetModelShape("cold", 100, 30, 31, F, buckets=(8, 64),
                        weight=1.0, age_s=300.0),
    ]
    big = plan_fleet(shapes, budget_bytes=1 << 30, accel=False)
    assert big.feasible and big.evicted == ()
    assert all(m.resident_buckets == (8, 64) for m in big.models)
    hot_cost = (big.models[0].forest_bytes + big.models[0].program_bytes)
    small = plan_fleet(shapes, budget_bytes=int((hot_cost + 512) / HEADROOM),
                       accel=False)
    assert small.evicted == ("cold",)
    assert not small.feasible
    assert small.models[0].resident
    # the verdict order follows the INPUT order, not the priority order
    assert [m.name for m in small.models] == ["hot", "cold"]
    # priority election: recency beats nominal weight — a hot low-weight
    # model keeps residency over a long-stale heavy one
    shapes2 = [
        FleetModelShape("stale", 100, 30, 31, F, buckets=(8,), weight=4.0,
                        age_s=1e6),
        FleetModelShape("fresh", 100, 30, 31, F, buckets=(8,), weight=1.0),
    ]
    one_cost = (predict_forest_bytes(100, 30, 31, accel=False)
                + predict_program_bytes(100, 8, F, accel=False))
    one = plan_fleet(
        shapes2, budget_bytes=int((one_cost + 512) / HEADROOM), accel=False)
    assert one.evicted == ("stale",)


def test_plan_fleet_partial_bucket_residency():
    shapes = [FleetModelShape("m", 200, 60, 61, F,
                              buckets=(8, 512, 4096), weight=1.0)]
    fb = predict_forest_bytes(200, 60, 61, accel=False)
    small_prog = predict_program_bytes(200, 8, F, accel=False)
    mid_prog = predict_program_bytes(200, 512, F, accel=False)
    plan = plan_fleet(
        shapes, budget_bytes=int((fb + small_prog + mid_prog + 256)
                                 / HEADROOM), accel=False)
    (mp,) = plan.models
    assert mp.resident
    # smallest-first bucket admission: 8 and 512 fit, 4096 does not
    assert mp.resident_buckets == (8, 512)
    assert plan.feasible          # the model IS resident; buckets degrade


def test_predict_forest_bytes_precision_ladder():
    f32 = predict_forest_bytes(100, 30, 31, "f32", accel=False)
    bf16 = predict_forest_bytes(100, 30, 31, "bf16", accel=False,
                                routing_only=True)
    int8 = predict_forest_bytes(100, 30, 31, "int8", accel=False,
                                routing_only=True)
    assert f32 > bf16 > int8
    assert predict_forest_bytes(200, 30, 31, accel=False) > f32
    assert predict_program_bytes(100, 64, F, accel=False) > \
        predict_program_bytes(100, 8, F, accel=False)


# ------------------------------------------------------- default parity


def test_fleet_default_bit_parity(boosters):
    fleet = _fleet3(boosters)
    try:
        rng = np.random.RandomState(5)
        for name, b in zip(("m0", "m1", "m2"), boosters):
            X = _f32_data(rng, 33)
            out = fleet.predict(name, X, timeout=60)
            assert np.array_equal(out, b.predict(X, raw_score=True)), name
    finally:
        fleet.close()


def test_fleet_unknown_model_and_classes(boosters):
    fleet = _fleet3(boosters)
    try:
        with pytest.raises(ModelNotFound):
            fleet.predict("nope", np.zeros((1, F)))
        with pytest.raises(ValueError):
            fleet.add_model("bad_class", boosters[0],
                            deadline_class="warp-speed")
        with pytest.raises(ValueError):
            fleet.add_model("m0", boosters[0])     # duplicate name
        with pytest.raises(ValueError):
            fleet.add_model("w", boosters[0], weight=0.0)
    finally:
        fleet.close()


def test_fleet_traffic_mix_loadgen(boosters):
    from lightgbm_tpu.serving.loadgen import fire_fleet_requests
    fleet = _fleet3(boosters)
    try:
        verify = {}
        for name, b in zip(("m0", "m1", "m2"), boosters):
            n_iter = len(b.models) // b.num_tree_per_iteration
            verify[name] = b._forest(0, n_iter)
        storm = fire_fleet_requests(
            fleet, {"m0": 3.0, "m1": 1.0, "m2": 1.0}, n_requests=60,
            n_threads=4, max_request_rows=100, verify=verify, timeout=60)
        assert storm["errors"] == []
        assert storm["mismatches"] == 0
        assert storm["requests"] + storm["shed"] + storm["expired"] \
            == storm["requests_planned"]
        # per-model latency percentiles ride the summary
        for name in ("m0", "m1", "m2"):
            s = storm["models"][name]
            if s["requests"]:
                assert set(s["latency_ms"]) >= {"p50", "p90", "p99"}
        # weighted draw: the weight-3 model sees the most traffic
        assert storm["models"]["m0"]["requests"] >= \
            storm["models"]["m1"]["requests"]
    finally:
        fleet.close()


# ------------------------------------------------------------- eviction


def test_fleet_eviction_keeps_models_servable(boosters):
    fleet = _fleet3(boosters)
    try:
        fleet.config.hbm_budget_bytes = _hot_only_budget(fleet)
        plan = fleet.replan()
        assert len(plan.evicted) >= 1 and "m0" not in plan.evicted
        rng = np.random.RandomState(6)
        for name, b in zip(("m0", "m1", "m2"), boosters):
            X = _f32_data(rng, 21)
            out = fleet.predict(name, X, timeout=60)
            assert np.array_equal(out, b.predict(X, raw_score=True)), name
        for name in plan.evicted:
            e = fleet.entry(name)
            assert e.model.device_forest is None
            assert not e.resident
        c = fleet.metrics_dict()["counters"]
        assert sum(v for k, v in c.items()
                   if k.startswith("fleet_evictions")) == len(plan.evicted)
    finally:
        fleet.close()


def test_fleet_evict_then_restore_round_trip(boosters):
    fleet = _fleet3(boosters)
    try:
        fleet.config.hbm_budget_bytes = _hot_only_budget(fleet)
        plan = fleet.replan()
        evicted = plan.evicted
        assert evicted
        fleet.config.hbm_budget_bytes = None
        plan2 = fleet.replan()
        assert plan2.evicted == ()
        rng = np.random.RandomState(7)
        for name in evicted:
            e = fleet.entry(name)
            assert e.model.device_forest is not None and e.resident
            b = boosters[int(name[1:])]
            X = _f32_data(rng, 17)
            assert np.array_equal(fleet.predict(name, X, timeout=60),
                                  b.predict(X, raw_score=True))
        c = fleet.metrics_dict()["counters"]
        assert sum(v for k, v in c.items()
                   if k.startswith("fleet_restores")) == len(evicted)
    finally:
        fleet.close()


def test_fleet_eviction_under_load(boosters):
    """Replanning back and forth WHILE requests are in flight: no
    errors, every response still bit-equal (programs read the device
    pointer at call time; the host fallback is bit-identical)."""
    fleet = _fleet3(boosters)
    tiny = _hot_only_budget(fleet)
    stop = threading.Event()
    flips = [0]

    def churn():
        while not stop.is_set():
            fleet.config.hbm_budget_bytes = \
                tiny if flips[0] % 2 == 0 else None
            fleet.replan()
            flips[0] += 1

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    try:
        rng = np.random.RandomState(8)
        for i in range(30):
            name = f"m{i % 3}"
            b = boosters[i % 3]
            X = _f32_data(rng, 1 + (i * 7) % 64)
            out = fleet.predict(name, X, timeout=60)
            assert np.array_equal(out, b.predict(X, raw_score=True))
    finally:
        stop.set()
        t.join(timeout=10)
        fleet.close()
    assert flips[0] >= 1


# ---------------------------------------------------- weighted admission


def test_weighted_admission_sheds_over_share(boosters):
    fleet = _fleet3(boosters, max_queue_rows=1000)
    try:
        heavy, light = fleet.entry("m0"), fleet.entry("m1")
        # fake a saturated queue: the fleet-wide total is over cap, the
        # heavy model holds most of it
        heavy.server._batcher._queued_rows = 900
        light.server._batcher._queued_rows = 90
        try:
            # m0 (weight 3/5 -> share 600 rows) is OVER its share: shed
            with pytest.raises(QueueFull):
                fleet._admit(heavy, 50)
            # m1 (weight 1/5 -> share 200 rows) is under its share even
            # though the fleet is saturated: protected, admitted
            fleet._admit(light, 50)
            c = fleet.metrics_dict()["counters"]
            assert c['fleet_shed_total{model="m0"}'] == 1
            assert 'fleet_shed_total{model="m1"}' not in c
        finally:
            heavy.server._batcher._queued_rows = 0
            light.server._batcher._queued_rows = 0
    finally:
        fleet.close()


def test_deadline_class_applies_default_deadline(boosters):
    fleet = _fleet3(boosters)
    try:
        # give the interactive class an unmeetable deadline: the batcher
        # must reject the request at pop time with DeadlineExceeded
        fleet.config.deadline_classes["interactive"] = 1e-7
        from lightgbm_tpu.serving import DeadlineExceeded
        with pytest.raises(DeadlineExceeded):
            fleet.predict("m0", np.zeros((4, F)), timeout=60)
        # an explicit per-request deadline overrides the class default
        out = fleet.predict("m0", np.zeros((4, F)), deadline_ms=60_000,
                            timeout=60)
        assert out.shape == (4,)
        # the "batch" class (None) imposes no deadline
        assert fleet.predict("m2", np.zeros((4, F)), timeout=60) is not None
    finally:
        fleet.close()


# ------------------------------------------------------------------ AOT


def test_aot_store_roundtrip(tmp_path, boosters):
    srv = boosters[0].serve(max_batch_rows=64)
    try:
        n = srv.export_aot(path=str(tmp_path))
        assert n == len(srv.ladder.buckets)
        store = AOTStore(str(tmp_path))
        model = srv.models.active
        assert store.buckets_for(model.digest) == srv.ladder.buckets
        fn = store.load_leaves(model.digest, 16)
        X = _f32_data(np.random.RandomState(3), 16).astype(np.float32)
        got = np.asarray(fn(X))
        want = np.asarray(model.device_forest._leaves_jit(X))
        assert np.array_equal(got, want)
        assert store.load_leaves(model.digest, 4096) is None   # miss
        assert store.load_leaves("feedface00000000", 16) is None
    finally:
        srv.close()


def test_aot_replica_first_request_zero_compiles(tmp_path, boosters):
    fleet = _fleet3(boosters)
    exported = fleet.export_aot(str(tmp_path))
    fleet.close()
    assert exported == 3 * 5            # 3 models x ladder 8..128
    replica = _fleet3(boosters, aot_dir=str(tmp_path))
    try:
        replica.warm()
        rng = np.random.RandomState(4)
        for name, b in zip(("m0", "m1", "m2"), boosters):
            X = _f32_data(rng, 40)
            out = replica.predict(name, X, timeout=60)
            assert np.array_equal(out, b.predict(X, raw_score=True)), name
        for name in ("m0", "m1", "m2"):
            c = replica.entry(name).server.metrics_dict()["counters"]
            assert c.get("compile_events", 0) == 0, name
            assert c.get("aot_program_loads", 0) >= 1, name
    finally:
        replica.close()


def test_aot_corrupt_entry_is_a_miss_not_a_failure(tmp_path, boosters):
    srv = boosters[0].serve(max_batch_rows=64)
    digest = srv.models.active.digest
    srv.export_aot(path=str(tmp_path))
    srv.close()
    # corrupt one blob, truncate another's metadata
    with open(os.path.join(str(tmp_path), f"{digest}-b16.bin"), "wb") as fh:
        fh.write(b"not a stablehlo module")
    with open(os.path.join(str(tmp_path), f"{digest}-b32.json"), "w") as fh:
        fh.write("{")
    srv2 = lgb.serve(boosters[0], max_batch_rows=64,
                     aot_dir=str(tmp_path))
    try:
        rng = np.random.RandomState(5)
        for rows in (16, 32, 8):
            X = _f32_data(rng, rows)
            out = srv2.predict(X, timeout=60)
            assert np.array_equal(out,
                                  boosters[0].predict(X, raw_score=True))
        c = srv2.metrics_dict()["counters"]
        # corrupted buckets compiled fresh, intact ones restored
        assert c.get("compile_events", 0) >= 2
        assert c.get("aot_program_loads", 0) >= 1
    finally:
        srv2.close()


def test_aot_version_and_platform_gate(tmp_path, boosters):
    srv = boosters[0].serve(max_batch_rows=64)
    digest = srv.models.active.digest
    srv.export_aot(path=str(tmp_path))
    srv.close()
    store = AOTStore(str(tmp_path))
    meta_path = os.path.join(str(tmp_path), f"{digest}-b16.json")
    meta = json.load(open(meta_path))
    meta["platforms"] = ["tpu_v9"]
    json.dump(meta, open(meta_path, "w"))
    assert store.load_leaves(digest, 16) is None
    meta["platforms"] = ["cpu"]
    meta["version"] = 999
    json.dump(meta, open(meta_path, "w"))
    assert store.load_leaves(digest, 16) is None
    assert store.load_leaves(digest, 8) is not None


# ------------------------------------------------------- low precision


def test_quantize_forest_grids(boosters):
    b = boosters[0]
    n_iter = len(b.models) // b.num_tree_per_iteration
    forest = b._forest(0, n_iter)
    qf = quantize_forest(forest, "bf16")
    import ml_dtypes
    finite = np.isfinite(forest.threshold) & ~forest.is_cat
    # bf16 grid: a second rounding is the identity
    assert np.array_equal(
        qf.threshold[finite],
        qf.threshold[finite].astype(ml_dtypes.bfloat16).astype(np.float64))
    # +inf padding and leaf grid
    assert np.array_equal(qf.threshold[~finite], forest.threshold[~finite])
    assert np.array_equal(
        qf.leaf_value, qf.leaf_value.astype(ml_dtypes.bfloat16)
        .astype(np.float64))
    q8 = quantize_forest(forest, "int8")
    # per-tree int8: at most 255 distinct levels per tree
    for t in range(q8.leaf_value.shape[0]):
        assert len(np.unique(q8.leaf_value[t])) <= 255
    assert q8.threshold_q.dtype == np.int8
    # the carried codes reproduce the grid exactly: q * scale == threshold
    deq = (q8.threshold_q.astype(np.float32)
           * q8.threshold_scale[:, None]).astype(np.float64)
    assert np.array_equal(deq[~q8.threshold_skip],
                          q8.threshold[~q8.threshold_skip])
    with pytest.raises(ValueError):
        quantize_forest(forest, "fp4")


def test_int8_rows_skip_mask():
    a = np.array([[1.0, -2.0, np.inf], [0.0, 0.0, 0.0]])
    q, scale, deq = int8_rows(a)
    assert q[0, 2] == 0 and deq[0, 2] == np.inf
    assert np.all(q[1] == 0) and np.all(deq[1] == 0.0)
    assert abs(deq[0, 1] - (-2.0)) <= 2.0 / 127


def test_lowprec_serves_quantized_forest_bitwise(boosters):
    """The opt-in path serves EXACTLY the quantized twin: device output
    bit-equal to the quantized forest's host predict_raw, and the
    measured delta within the declared budget."""
    b = boosters[0]
    fleet = Fleet(max_batch_rows=128)
    try:
        fleet.add_model("full", b)
        for prec in ("bf16", "int8"):
            e = fleet.add_model(prec, b, precision=prec,
                                accuracy_budget=1.0)
            delta = e.server.metrics.gauge("lowprec_accuracy_delta").value
            assert 0 < delta <= 1.0
            rng = np.random.RandomState(11)
            X = _f32_data(rng, 50)
            out = fleet.predict(prec, X, timeout=60)
            qf = e.model.forest
            assert np.array_equal(out, qf.predict_raw(X)[0]), prec
            # and the full-precision member still bit-matches the booster
            assert np.array_equal(fleet.predict("full", X, timeout=60),
                                  b.predict(X, raw_score=True))
            # served drift stays within the probe-declared order
            drift = np.max(np.abs(out - b.predict(X, raw_score=True)))
            assert drift <= 1.0
    finally:
        fleet.close()


def test_lowprec_budget_quarantines_add_and_swap(boosters):
    fleet = Fleet(max_batch_rows=128)
    try:
        fleet.add_model("m", boosters[0])
        with pytest.raises(LowPrecisionQuarantined):
            fleet.add_model("tight", boosters[0], precision="int8",
                            accuracy_budget=0.0)
        assert fleet.models() == ["m"]       # nothing half-registered
        # swap path: a registered lowprec member holds ITS budget across
        # swaps — a candidate over it is quarantined, old model serves on
        e = fleet.add_model("lp", boosters[0], precision="bf16",
                            accuracy_budget=1.0)
        old_digest = e.model.digest
        e.server.models.accuracy_budget = 1e-12
        with pytest.raises(LowPrecisionQuarantined):
            fleet.swap_model("lp", boosters[1])
        assert e.model.digest == old_digest
        c = e.server.metrics_dict()["counters"]
        assert c.get("lowprec_quarantines", 0) >= 1
        assert c.get("swap_quarantines", 0) >= 1
        X = _f32_data(np.random.RandomState(2), 9)
        assert fleet.predict("lp", X, timeout=60) is not None
    finally:
        fleet.close()


def test_lowprec_caller_probe_batch(boosters):
    """A caller-supplied probe batch drives the measurement (real data
    routes more realistically than noise)."""
    b = boosters[0]
    rng = np.random.RandomState(13)
    probe = _f32_data(rng, 64)
    n_iter = len(b.models) // b.num_tree_per_iteration
    forest = b._forest(0, n_iter)
    expected = measure_accuracy_delta(forest,
                                      quantize_forest(forest, "bf16"), probe)
    srv = lgb.serve(b, max_batch_rows=64, precision="bf16",
                    accuracy_budget=1.0, probe_X=probe)
    try:
        got = srv.metrics.gauge("lowprec_accuracy_delta").value
        assert got == expected
    finally:
        srv.close()


# ------------------------------------------------------------- metrics


def test_fleet_prometheus_labels(boosters):
    fleet = _fleet3(boosters)
    try:
        fleet.predict("m0", np.zeros((3, F)), timeout=60)
        text = fleet.prometheus_text()
        assert 'lgbt_fleet_fleet_requests_total{model="m0"} 1' in text
        assert 'lgbt_fleet_model_weight{model="m0"} 3.0' in text
        assert 'lgbt_fleet_model_resident{model="m1"} 1' in text
        d0 = fleet.entry("m0").model.digest
        assert f'lgbt_fleet_model_digest_info{{model="m0",value="{d0}"}} 1' \
            in text
        # labelled histogram series merge per-sample le labels
        assert 'lgbt_fleet_request_latency_ms_bucket{le="+Inf",model="m0"}' \
            in text
        # every sample line still ends in a parseable number
        for line in text.strip().splitlines():
            if not line.startswith("#"):
                float(line.rsplit(" ", 1)[1])
        # to_dict: labelled series are ADDITIVE suffixed keys
        d = fleet.metrics_dict()
        assert d["counters"]['fleet_requests_total{model="m0"}'] == 1
        assert "servers" in d and set(d["servers"]) == {"m0", "m1", "m2"}
        # each member server's own layout is unchanged
        assert "requests_total" in d["servers"]["m0"]["counters"]
    finally:
        fleet.close()


def test_fleet_joins_process_registry(boosters):
    from lightgbm_tpu.obs.metrics import global_registry
    fleet = _fleet3(boosters)
    try:
        comp = global_registry.to_dict().get("components", {})
        assert any(k.startswith("fleet") for k in comp)
    finally:
        fleet.close()
    comp = global_registry.to_dict().get("components", {})
    assert not any(k.startswith("fleet") for k in comp)


# ------------------------------------------------------------- lifecycle


def test_remove_and_swap_replan(boosters):
    fleet = _fleet3(boosters)
    try:
        fleet.remove_model("m2")
        assert fleet.models() == ["m0", "m1"]
        with pytest.raises(ModelNotFound):
            fleet.predict("m2", np.zeros((1, F)))
        fleet.swap_model("m1", boosters[2])     # class-count change
        X = _f32_data(np.random.RandomState(3), 12)
        assert np.array_equal(fleet.predict("m1", X, timeout=60),
                              boosters[2].predict(X, raw_score=True))
        assert len(fleet.plan.models) == 2
    finally:
        fleet.close()


@pytest.mark.slow
def test_fleet_stress_mixed_traffic_with_churn(boosters):
    """Concurrent weighted traffic against 3 models while residency
    churns: honest completed counts, zero mismatches, zero errors."""
    from lightgbm_tpu.serving.loadgen import fire_fleet_requests
    fleet = _fleet3(boosters)
    verify = {}
    for name, b in zip(("m0", "m1", "m2"), boosters):
        n_iter = len(b.models) // b.num_tree_per_iteration
        verify[name] = b._forest(0, n_iter)
    tiny = _hot_only_budget(fleet)
    stop = threading.Event()

    def churn():
        i = 0
        while not stop.is_set():
            fleet.config.hbm_budget_bytes = tiny if i % 2 == 0 else None
            fleet.replan()
            i += 1

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    try:
        storm = fire_fleet_requests(
            fleet, {"m0": 3.0, "m1": 1.0, "m2": 1.0}, n_requests=400,
            n_threads=8, max_request_rows=120, verify=verify, timeout=120)
    finally:
        stop.set()
        t.join(timeout=10)
        fleet.close()
    assert storm["errors"] == []
    assert storm["mismatches"] == 0
    assert storm["requests"] + storm["shed"] + storm["expired"] \
        == storm["requests_planned"]
