"""Bulk offline scoring: ScoreSink commit protocol, block sharding,
crash-resume byte-identity (data/score.py).

The sink's manifest is rewritten atomically after EVERY banked block —
so a kill at any instant leaves a manifest naming exactly the blocks
whose bytes are on disk, and a resume skips them and reproduces the
rest byte-for-byte (the per-row float64 epilogue makes block boundaries
bit-invisible).
"""

import filecmp
import os
from types import SimpleNamespace

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.data.blockstore import BlockStore
from lightgbm_tpu.data.score import (BulkScorer, ScoreSink, ScoreSinkError,
                                     plan_block_shards)
from lightgbm_tpu.predict import DeviceForest

BLOCK_ROWS = 512
ROWS = 2200           # 5 blocks, ragged tail (2200 = 4*512 + 152)


def _dev(slice_id, device_id):
    return SimpleNamespace(slice_id=slice_id, device_id=device_id)


# ----------------------------------------------------------------------
# ScoreSink
# ----------------------------------------------------------------------


def _mk_sink(path, num_blocks=3, num_class=1):
    return ScoreSink.open_or_create(
        str(path), num_rows=num_blocks * BLOCK_ROWS, num_class=num_class,
        block_rows=BLOCK_ROWS, num_blocks=num_blocks, model_digest="d1")


def test_sink_write_read_roundtrip(tmp_path):
    sink = _mk_sink(tmp_path / "s")
    rng = np.random.RandomState(0)
    b0 = rng.randn(1, BLOCK_ROWS)
    sink.write_block(0, b0)
    assert sink.banked() == {0} and not sink.complete
    np.testing.assert_array_equal(sink.read_block(0), b0)
    with pytest.raises(ScoreSinkError, match="not banked"):
        sink.read_block(1)


def test_sink_reopen_resumes_banked_blocks(tmp_path):
    sink = _mk_sink(tmp_path / "s")
    sink.write_block(1, np.ones((1, BLOCK_ROWS)))
    again = _mk_sink(tmp_path / "s")
    assert again.banked() == {1}
    again.write_block(0, np.zeros((1, BLOCK_ROWS)))
    again.write_block(2, np.zeros((1, 152)))        # ragged tail block
    assert again.complete
    assert again.read_block(2).shape == (1, 152)


def test_sink_rejects_foreign_geometry(tmp_path):
    _mk_sink(tmp_path / "s")
    for kw in ({"num_blocks": 4}, {"num_class": 2}):
        args = dict(num_rows=3 * BLOCK_ROWS, num_class=1,
                    block_rows=BLOCK_ROWS, num_blocks=3, model_digest="d1")
        args.update(kw)
        with pytest.raises(ScoreSinkError, match="disagrees"):
            ScoreSink.open_or_create(str(tmp_path / "s"), **args)
    with pytest.raises(ScoreSinkError, match="disagrees"):
        ScoreSink.open_or_create(
            str(tmp_path / "s"), num_rows=3 * BLOCK_ROWS, num_class=1,
            block_rows=BLOCK_ROWS, num_blocks=3, model_digest="OTHER")


def test_sink_detects_corrupt_block(tmp_path):
    sink = _mk_sink(tmp_path / "s")
    sink.write_block(0, np.ones((1, BLOCK_ROWS)))
    fp = tmp_path / "s" / "scores_00000.bin"
    raw = bytearray(fp.read_bytes())
    raw[3] ^= 0xFF
    fp.write_bytes(bytes(raw))
    with pytest.raises(ScoreSinkError, match="checksum"):
        sink.read_block(0)


def test_sink_rejects_wrong_shape(tmp_path):
    sink = _mk_sink(tmp_path / "s", num_class=2)
    with pytest.raises(ValueError, match=r"\[2, rows\]"):
        sink.write_block(0, np.ones((1, BLOCK_ROWS)))


# ----------------------------------------------------------------------
# block sharding
# ----------------------------------------------------------------------


def test_shards_single_device():
    assert plan_block_shards(4, [_dev(0, 7)]) == (7, 7, 7, 7)


def test_shards_ici_before_dcn():
    """The coordinator's slice fills first; the remote slice's devices
    take spillover LAST, whatever order the specs arrive in."""
    devs = [_dev(1, 10), _dev(0, 20), _dev(1, 11)]   # home slice = 1
    assert plan_block_shards(6, devs) == (10, 11, 20, 10, 11, 20)


def test_shards_empty_devices_raise():
    with pytest.raises(ValueError):
        plan_block_shards(3, [])


# ----------------------------------------------------------------------
# BulkScorer end-to-end: scores, crash-resume, byte-identity
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def scoring_setup(tmp_path_factory):
    root = tmp_path_factory.mktemp("bulk")
    rng = np.random.RandomState(5)
    X = rng.randn(ROWS, 6).astype(np.float32)
    X[rng.rand(ROWS) < 0.1, 1] = np.nan
    y = (X[:, 0] + X[:, 2] > 0).astype(float)
    bst = lgb.train(
        {"objective": "binary", "verbosity": -1, "num_leaves": 15,
         "min_data_in_leaf": 5},
        lgb.Dataset(X.astype(np.float64), label=y),
        num_boost_round=6, verbose_eval=False)
    forest = bst._forest(0, len(bst.models))
    store = BlockStore.from_array(
        str(root / "features"), X, block_rows=BLOCK_ROWS)
    return root, bst, forest, store, X


def test_bulk_scores_match_booster(scoring_setup):
    root, bst, forest, store, X = scoring_setup
    dev = DeviceForest(forest, variant="fori")
    stats = BulkScorer(dev, store, str(root / "sink_full")).run()
    assert stats["complete"] and stats["blocks_scored"] == store.num_blocks
    assert stats["rows_scored"] == ROWS
    sink = ScoreSink.open_or_create(
        str(root / "sink_full"), ROWS, 1, BLOCK_ROWS, store.num_blocks,
        BulkScorer(dev, store, str(root / "sink_full")).digest)
    got = np.concatenate(
        [sink.read_block(i) for i in range(store.num_blocks)], axis=1)[0]
    ref = bst.predict(X.astype(np.float64), raw_score=True)
    assert np.array_equal(got, ref), \
        "bulk scores are not bit-identical to Booster.predict(raw_score)"


def test_bulk_crash_resume_byte_identical(scoring_setup):
    root, bst, forest, store, X = scoring_setup
    dev = DeviceForest(forest, variant="fori")
    a = str(root / "sink_a")
    b = str(root / "sink_b")
    BulkScorer(dev, store, a).run()

    cut = 2
    partial = BulkScorer(dev, store, b).run(max_blocks=cut)
    assert partial["blocks_scored"] == cut and not partial["complete"]
    resumed = BulkScorer(dev, store, b).run()       # fresh scorer: resume
    assert resumed["skipped_blocks"] == cut
    assert resumed["blocks_scored"] == store.num_blocks - cut
    assert resumed["complete"]

    names = sorted(f for f in os.listdir(a) if f.endswith(".bin"))
    assert names == sorted(f for f in os.listdir(b) if f.endswith(".bin"))
    for f in names:
        assert filecmp.cmp(os.path.join(a, f), os.path.join(b, f),
                           shallow=False), f"resumed block {f} diverged"


def test_bulk_refuses_non_f32_store(tmp_path, scoring_setup):
    _, _, forest, _, _ = scoring_setup
    q = BlockStore.from_array(
        str(tmp_path / "u8"),
        np.zeros((64, 3), np.uint8), block_rows=32)
    with pytest.raises(ValueError, match="float32"):
        BulkScorer(DeviceForest(forest, variant="fori"), q,
                   str(tmp_path / "sink"))


def test_bulk_sharded_run_scores_only_its_blocks(scoring_setup):
    """Two devices: each participant banks only its shard; together they
    complete the sink."""
    root, bst, forest, store, X = scoring_setup
    dev = DeviceForest(forest, variant="fori")
    devs = [_dev(0, 0), _dev(0, 1)]
    sink = str(root / "sink_sharded")
    s0 = BulkScorer(dev, store, sink, devices=devs, local_device_id=0).run()
    assert not s0["complete"]
    assert s0["blocks_scored"] == (store.num_blocks + 1) // 2
    s1 = BulkScorer(dev, store, sink, devices=devs, local_device_id=1).run()
    assert s1["complete"]
    assert s0["blocks_scored"] + s1["blocks_scored"] == store.num_blocks
