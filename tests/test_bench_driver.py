"""The driver contract: bench.py must ALWAYS leave a parseable JSON result
line as its last stdout line (round 4 failed with parsed=null after a
budget-exhausted TPU wedge — the fix is staged emission + a concurrent
CPU fallback whose result is banked the moment it exists)."""

import json
import os
import subprocess
import sys


def test_bench_cpu_pipeline_emits_parseable_result():
    env = dict(os.environ)
    env.update({
        "BENCH_FORCE_CPU": "1",
        "BENCH_CPU_ROWS": "20000",
        "BENCH_CPU_TREES": "5",
        "BENCH_BUDGET": "300",
        "JAX_PLATFORMS": "cpu",
    })
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=280, env=env, cwd=repo)
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, proc.stdout[-2000:] + proc.stderr[-2000:]
    last = json.loads(lines[-1])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in last, last
    assert last.get("sec_per_tree", 0) > 0, last
    assert "cpu" in last["metric"].lower(), last["metric"]
