"""The driver contract: bench.py must ALWAYS leave a parseable JSON result
line as its last stdout line (round 4 failed with parsed=null after a
budget-exhausted TPU wedge — the fix is staged emission + a concurrent
CPU fallback whose result is banked the moment it exists)."""

import json
import os
import subprocess
import sys


def test_bench_cpu_pipeline_emits_parseable_result():
    env = dict(os.environ)
    env.update({
        "BENCH_FORCE_CPU": "1",
        "BENCH_CPU_ROWS": "20000",
        "BENCH_CPU_TREES": "5",
        "BENCH_BUDGET": "300",
        "JAX_PLATFORMS": "cpu",
    })
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=280, env=env, cwd=repo)
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, proc.stdout[-2000:] + proc.stderr[-2000:]
    last = json.loads(lines[-1])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in last, last
    assert last.get("sec_per_tree", 0) > 0, last
    assert "cpu" in last["metric"].lower(), last["metric"]


def _run_worker(env_extra, timeout=240):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({
        "BENCH_STAGE": "tpu-worker",
        "BENCH_WORKER_ALLOW_CPU": "1",
        "BENCH_ROWS": "5000",
        "BENCH_TREES": "3",
        "BENCH_LEAVES": "15",
        "BENCH_BIN": "63",
        "JAX_PLATFORMS": "cpu",
    })
    env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=repo)
    stages = []
    for ln in proc.stdout.strip().splitlines():
        try:
            obj = json.loads(ln)
        except ValueError:
            continue
        if isinstance(obj, dict) and obj.get("stage"):
            stages.append(obj)
    return stages


def test_bench_journal_resume_after_crash(tmp_path):
    """Stage-journal contract: a run that dies after banking a stage must
    NOT re-execute it on rerun — the journal replays it and only the
    missing stages run (round 5 lost ranking+epsilon to exactly this)."""
    journal = str(tmp_path / "journal.json")
    # first run "crashes" after kernel_probe (only that stage selected)
    s1 = _run_worker({"BENCH_JOURNAL": journal,
                      "BENCH_ONLY": "kernel_probe"})
    assert any(s["stage"] == "kernel_probe" and "error" not in s
               for s in s1), s1
    d = json.load(open(journal))
    assert "kernel_probe" in d["stages"]

    # rerun wants kernel_probe + hist_probe: the first must come from the
    # journal (no re-execution), the second runs fresh and is banked
    s2 = _run_worker({"BENCH_JOURNAL": journal,
                      "BENCH_ONLY": "kernel_probe,hist_probe"})
    kp = [s for s in s2 if s["stage"] == "kernel_probe"]
    hp = [s for s in s2 if s["stage"] == "hist_probe"]
    assert kp and kp[0].get("journal") is True, kp
    assert hp and "error" not in hp[0] and "journal" not in hp[0], hp
    d = json.load(open(journal))
    assert set(d["stages"]) == {"kernel_probe", "hist_probe"}


def test_bench_collective_probe_stage(tmp_path):
    """The pod-scale collective micro-bench rides the stage journal like
    every probe: BENCH_ONLY selects it, the journaled result carries the
    per-tier byte fields, and the acceptance signal (voting DCN bytes
    strictly below data-parallel at equal trees) holds."""
    journal = str(tmp_path / "journal.json")
    stages = _run_worker({"BENCH_JOURNAL": journal,
                          "BENCH_ONLY": "collective_probe"})
    cp = [s for s in stages
          if s["stage"] == "collective_probe" and "error" not in s]
    assert cp, stages
    out = cp[0]
    assert {"mesh_shape", "ici_bytes", "dcn_bytes", "hierarchy_elected",
            "voting_k", "measured_ms"} <= out.keys(), sorted(out)
    for payload in ("f32", "quant"):
        assert out[payload]["voting_dcn_below_data"], out[payload]
        assert out[payload]["voting_parallel"]["dcn_bytes"] \
            < out[payload]["data_parallel"]["dcn_bytes"]
    d = json.load(open(journal))
    assert "collective_probe" in d["stages"]


def test_bench_diff_gate(tmp_path):
    """tools/bench_diff.py is the perf gate: an unchanged journal passes
    (exit 0), a synthetic 2x sec_per_tree regression is flagged by name
    with a nonzero exit, and the last stdout line is one JSON verdict."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = {"fingerprint": "fp", "stages": {
        "full@200000": {"sec_per_tree": 0.5, "value": 25.0,
                        "holdout_auc": 0.965, "iters_per_sec": 2.0,
                        "compile_seconds": 8.0,
                        "compile_cache": {"entries_after": 4}},
        "serving": {"p99_ms": 12.0, "qps": 900.0}}}
    a = tmp_path / "old.json"
    b = tmp_path / "new.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(base))

    def run(old, new, *extra):
        return subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "bench_diff.py"),
             str(old), str(new), *extra],
            capture_output=True, text=True, timeout=60)

    proc = run(a, b)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is True and verdict["regressions"] == []
    assert verdict["stages_compared"] == 2

    worse = json.loads(json.dumps(base))
    worse["stages"]["full@200000"]["sec_per_tree"] = 1.0     # 2x slower
    c = tmp_path / "regressed.json"
    c.write_text(json.dumps(worse))
    proc = run(a, c)
    assert proc.returncode == 1, proc.stdout
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is False
    regressed = {r["metric"] for r in verdict["regressions"]}
    assert regressed == {"sec_per_tree"}
    assert "REGRESSION" in proc.stdout

    # per-metric threshold override loosens the gate
    proc = run(a, c, "--threshold", "sec_per_tree=2.5")
    assert proc.returncode == 0, proc.stdout

    # a higher-is-better metric collapsing to ZERO must not slip through
    # the sub-noise-floor branch (qps=0 IS the regression)
    dead = json.loads(json.dumps(base))
    dead["stages"]["serving"]["qps"] = 0.0
    e = tmp_path / "collapsed.json"
    e.write_text(json.dumps(dead))
    proc = run(a, e)
    assert proc.returncode == 1, proc.stdout
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert {r["metric"] for r in verdict["regressions"]} == {"qps"}

    # a BENCH_r*.json driver file compares as stage "full" — but only
    # against a side that HAS a full stage; here: driver file vs itself
    d = tmp_path / "driver.json"
    d.write_text(json.dumps(
        {"n": 1, "rc": 0, "parsed": {"sec_per_tree": 0.7, "value": 35.0}}))
    proc = run(d, d)
    assert proc.returncode == 0, proc.stdout
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["stages_compared"] == 1

    # unreadable input is a distinct exit code, still one JSON line
    proc = run(a, tmp_path / "missing.json")
    assert proc.returncode == 2
    assert json.loads(proc.stdout.strip().splitlines()[-1])["ok"] is False


def test_bench_obs_doctor_stage(tmp_path):
    """The journaled obs_doctor stage (BENCH_SKIP_OBS honored, errors
    never journaled): runs last, emits ranked verdicts next to the
    banked telemetry, and banks under its own key."""
    journal = str(tmp_path / "journal.json")
    stages = _run_worker({"BENCH_JOURNAL": journal,
                          "BENCH_ONLY": "obs_doctor"})
    doc = [s for s in stages
           if s["stage"] == "obs_doctor" and "error" not in s]
    assert doc, stages
    out = doc[0]
    assert "top_verdict" in out and "verdicts" in out
    assert isinstance(out["verdicts"], list) and out["verdicts"]
    for v in out["verdicts"]:
        assert {"name", "score", "summary", "evidence"} <= set(v)
    d = json.load(open(journal))
    assert "obs_doctor" in d["stages"]


def test_bench_journal_fingerprint_invalidation(tmp_path, monkeypatch):
    """A journal written under a different workload shape must not be
    replayed (stale telemetry masquerading as current is worse than a
    rerun)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    journal = str(tmp_path / "j.json")
    monkeypatch.setenv("BENCH_JOURNAL", journal)
    monkeypatch.setenv("BENCH_ROWS", "1000")
    import importlib
    import bench
    importlib.reload(bench)
    bench.journal_put("smoke", {"value": 1.0})
    assert bench.journal_stages() == {"smoke": {"value": 1.0}}
    monkeypatch.setenv("BENCH_ROWS", "2000")
    importlib.reload(bench)
    assert bench.journal_stages() == {}
    importlib.reload(bench)  # leave module state consistent for others
