"""The driver contract: bench.py must ALWAYS leave a parseable JSON result
line as its last stdout line (round 4 failed with parsed=null after a
budget-exhausted TPU wedge — the fix is staged emission + a concurrent
CPU fallback whose result is banked the moment it exists)."""

import json
import os
import subprocess
import sys


def test_bench_cpu_pipeline_emits_parseable_result():
    env = dict(os.environ)
    env.update({
        "BENCH_FORCE_CPU": "1",
        "BENCH_CPU_ROWS": "20000",
        "BENCH_CPU_TREES": "5",
        "BENCH_BUDGET": "300",
        "JAX_PLATFORMS": "cpu",
    })
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=280, env=env, cwd=repo)
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, proc.stdout[-2000:] + proc.stderr[-2000:]
    last = json.loads(lines[-1])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in last, last
    assert last.get("sec_per_tree", 0) > 0, last
    assert "cpu" in last["metric"].lower(), last["metric"]


def _run_worker(env_extra, timeout=240):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({
        "BENCH_STAGE": "tpu-worker",
        "BENCH_WORKER_ALLOW_CPU": "1",
        "BENCH_ROWS": "5000",
        "BENCH_TREES": "3",
        "BENCH_LEAVES": "15",
        "BENCH_BIN": "63",
        "JAX_PLATFORMS": "cpu",
    })
    env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=repo)
    stages = []
    for ln in proc.stdout.strip().splitlines():
        try:
            obj = json.loads(ln)
        except ValueError:
            continue
        if isinstance(obj, dict) and obj.get("stage"):
            stages.append(obj)
    return stages


def test_bench_journal_resume_after_crash(tmp_path):
    """Stage-journal contract: a run that dies after banking a stage must
    NOT re-execute it on rerun — the journal replays it and only the
    missing stages run (round 5 lost ranking+epsilon to exactly this)."""
    journal = str(tmp_path / "journal.json")
    # first run "crashes" after kernel_probe (only that stage selected)
    s1 = _run_worker({"BENCH_JOURNAL": journal,
                      "BENCH_ONLY": "kernel_probe"})
    assert any(s["stage"] == "kernel_probe" and "error" not in s
               for s in s1), s1
    d = json.load(open(journal))
    assert "kernel_probe" in d["stages"]

    # rerun wants kernel_probe + hist_probe: the first must come from the
    # journal (no re-execution), the second runs fresh and is banked
    s2 = _run_worker({"BENCH_JOURNAL": journal,
                      "BENCH_ONLY": "kernel_probe,hist_probe"})
    kp = [s for s in s2 if s["stage"] == "kernel_probe"]
    hp = [s for s in s2 if s["stage"] == "hist_probe"]
    assert kp and kp[0].get("journal") is True, kp
    assert hp and "error" not in hp[0] and "journal" not in hp[0], hp
    d = json.load(open(journal))
    assert set(d["stages"]) == {"kernel_probe", "hist_probe"}


def test_bench_collective_probe_stage(tmp_path):
    """The pod-scale collective micro-bench rides the stage journal like
    every probe: BENCH_ONLY selects it, the journaled result carries the
    per-tier byte fields, and the acceptance signal (voting DCN bytes
    strictly below data-parallel at equal trees) holds."""
    journal = str(tmp_path / "journal.json")
    stages = _run_worker({"BENCH_JOURNAL": journal,
                          "BENCH_ONLY": "collective_probe"})
    cp = [s for s in stages
          if s["stage"] == "collective_probe" and "error" not in s]
    assert cp, stages
    out = cp[0]
    assert {"mesh_shape", "ici_bytes", "dcn_bytes", "hierarchy_elected",
            "voting_k", "measured_ms"} <= out.keys(), sorted(out)
    for payload in ("f32", "quant"):
        assert out[payload]["voting_dcn_below_data"], out[payload]
        assert out[payload]["voting_parallel"]["dcn_bytes"] \
            < out[payload]["data_parallel"]["dcn_bytes"]
    d = json.load(open(journal))
    assert "collective_probe" in d["stages"]


def test_bench_journal_fingerprint_invalidation(tmp_path, monkeypatch):
    """A journal written under a different workload shape must not be
    replayed (stale telemetry masquerading as current is worse than a
    rerun)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    journal = str(tmp_path / "j.json")
    monkeypatch.setenv("BENCH_JOURNAL", journal)
    monkeypatch.setenv("BENCH_ROWS", "1000")
    import importlib
    import bench
    importlib.reload(bench)
    bench.journal_put("smoke", {"value": 1.0})
    assert bench.journal_stages() == {"smoke": {"value": 1.0}}
    monkeypatch.setenv("BENCH_ROWS", "2000")
    importlib.reload(bench)
    assert bench.journal_stages() == {}
    importlib.reload(bench)  # leave module state consistent for others
