"""Streaming dataset construction + sparse input paths.

reference: the two-pass DatasetLoader never materializes a dense double
matrix (SampleTextDataFromFile / ExtractFeaturesFromFile push rows,
src/io/dataset_loader.cpp:775,1101); here construction walks one column at
a time so peak host memory stays near the caller's input + the uint8
binned matrix (VERDICT round-3 item 8).
"""
import tracemalloc

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.dataset import Dataset


def test_construct_no_full_float64_copy():
    """Peak PYTHON-heap growth during construct must stay well under the
    old full-float64-copy cost (n*f*8 bytes).  tracemalloc (numpy hooks
    into it) measures this process-locally, unlike ru_maxrss, whose
    process-lifetime high-water mark earlier tests can poison."""
    n, f = 1_500_000, 20
    X = np.random.RandomState(0).rand(n, f).astype(np.float32)
    ds = Dataset(X, label=np.zeros(n, np.float32), free_raw_data=False)
    tracemalloc.start()
    try:
        tracemalloc.reset_peak()
        ds.construct()
        _, peak = tracemalloc.get_traced_memory()
    finally:
        tracemalloc.stop()
    full_copy = n * f * 8
    binned = n * f  # uint8 result matrix, the legitimate allocation
    # budget: the binned matrix + one float64 column of scratch, with 2x
    # headroom — far under the old full-copy cost
    assert peak < binned + 0.25 * full_copy, (
        f"construct peak-allocated {peak / 1e6:.0f} MB "
        f"(old full-copy cost {full_copy / 1e6:.0f} MB)")
    assert ds.binned.shape == (n, len(ds.used_features))


def test_construct_float32_matches_float64():
    """Column-wise widening must bin identically to an up-front cast."""
    rng = np.random.RandomState(1)
    X32 = rng.rand(4000, 8).astype(np.float32)
    y = (X32[:, 0] > 0.5).astype(np.float32)
    d32 = Dataset(X32, label=y).construct()
    d64 = Dataset(X32.astype(np.float64), label=y).construct()
    np.testing.assert_array_equal(d32.binned, d64.binned)


def test_sparse_csr_end_to_end():
    """scipy CSR input constructs column-streamed (one dense column of
    scratch at a time) and trains; predictions agree with the dense path."""
    sps = pytest.importorskip("scipy.sparse")
    rng = np.random.RandomState(0)
    n, f = 3000, 30
    X = sps.random(n, f, density=0.08, random_state=0, format="csr")
    Xd = X.toarray()
    y = (np.asarray(X.sum(axis=1)).ravel()
         > np.median(np.asarray(X.sum(axis=1)))).astype(np.float32)
    params = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
              "min_data_in_leaf": 5}
    bs = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    bd = lgb.train(params, lgb.Dataset(Xd, label=y), num_boost_round=5)
    np.testing.assert_allclose(bs.predict(Xd), bd.predict(Xd), rtol=1e-6)
