"""Device-ingest parity matrix + planner election (ops/ingest.py).

The bucketize+pack kernel's one invariant is BYTE identity with the
host ``BinMapper.value_to_bin`` + ``_bin_block`` path — across missing
types, categorical lookup, EFB bundles, uint8/uint16 group dtypes and
ragged last blocks.  Off-accelerator the kernel interprets as the same
jnp math, so these tests pin ``LGBM_TPU_INGEST_KERNEL=kernel`` (the
bisect gate) to force the device arm on tiny CPU-sized data; the
planner tests exercise the ``"i-..."`` autotune family, the ledger
budget arm, and the env pins directly.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.data.stream import IngestPump
from lightgbm_tpu.ops import ingest as ING
from lightgbm_tpu.ops import planner as P

GB = 1 << 30


def _raw(rows=3000, features=8, seed=0, categorical=True):
    """Every binning recipe at once: a categorical column, NaN routing,
    two mostly-zero columns (EFB actually bundles)."""
    rng = np.random.RandomState(seed)
    X = (rng.rand(rows, features) * 10.0).astype(np.float32)
    if categorical:
        X[:, 0] = rng.randint(0, 12, size=rows)
    X[rng.rand(rows) < 0.1, 2] = np.nan
    X[rng.rand(rows) < 0.7, 3] = 0.0
    X[rng.rand(rows) < 0.8, 5] = 0.0
    y = (rng.rand(rows) > 0.5).astype(np.float64)
    return X, y


def _dataset(X, y, max_bin=63, categorical=True):
    ds = lgb.Dataset(X, label=y,
                     params={"verbosity": -1, "max_bin": max_bin},
                     categorical_feature=[0] if categorical else None)
    ds.construct()
    return ds


def _host_ref(ds, X):
    ref = np.zeros((X.shape[0], ds.num_groups), ds.binned.dtype)
    with np.errstate(invalid="ignore"):
        ds._bin_block(np.asarray(X, np.float64), None, ref)
    return ref


# ---------------------------------------------------------------------
# byte identity
# ---------------------------------------------------------------------

@pytest.mark.parametrize("max_bin,categorical", [
    (63, True),        # uint8 groups + categorical + NaN + zero-as-bin
    (63, False),       # uint8, numerical only
    (1000, True),      # >256 bins -> uint16 groups
])
def test_construct_byte_identity(monkeypatch, max_bin, categorical):
    """The whole construct path: env-pinned kernel binning produces the
    exact bytes host binning does, salted rows included."""
    X, y = _raw()
    host = _dataset(X.copy(), y, max_bin, categorical)
    monkeypatch.setenv("LGBM_TPU_INGEST_KERNEL", "kernel")
    dev = _dataset(X.copy(), y, max_bin, categorical)
    assert dev.binned.dtype == host.binned.dtype
    assert np.array_equal(dev.binned, host.binned)
    story = ING.ingest_last()
    assert story.get("path") == "kernel"
    assert story.get("elected_by") == "env"
    assert story.get("parity_probe") is True


def test_binner_salted_block_parity(monkeypatch):
    """DeviceBinner directly vs the host oracle on the salt rows (all
    edge cases: zeros, all-NaN, +-1e30, non-integers, negative codes)."""
    X, y = _raw()
    ds = _dataset(X, y)
    tables = ING.build_ingest_tables(ds)
    binner = ING.DeviceBinner(tables, tile_rows=256)
    probe = np.concatenate([X[:300], ING.salt_rows(X.shape[1], X)])
    assert np.array_equal(np.asarray(binner(probe)), _host_ref(ds, probe))


def test_ragged_last_tile_and_block(monkeypatch):
    """Rows that are a multiple of neither the VMEM tile nor the pump
    chunk: padding rows must never leak into the committed bytes."""
    X, y = _raw(rows=2000 + 137)
    host = _dataset(X.copy(), y)
    monkeypatch.setenv("LGBM_TPU_INGEST_KERNEL", "kernel")
    monkeypatch.setenv("LGBM_TPU_INGEST_CHUNK", "700")   # 4 blocks, ragged
    dev = _dataset(X.copy(), y)
    assert np.array_equal(dev.binned, host.binned)


def test_float64_raw_stays_on_host(monkeypatch):
    """The directed-rounded boundary table is exact only against f32
    input; f64 raw must take the host oracle even when env-pinned."""
    monkeypatch.setenv("LGBM_TPU_INGEST_KERNEL", "kernel")
    X, y = _raw()
    ds = _dataset(X.astype(np.float64), y)
    out = np.zeros((100, ds.num_groups), ds.binned.dtype)
    assert not ds._maybe_device_bin(X[:100].astype(np.float64), None, out)


def test_parity_failure_demotes_for_good(monkeypatch):
    """A diverging probe must demote the dataset permanently (never
    wrong bytes), leave the host result intact, and say why."""
    X, y = _raw()
    host = _dataset(X.copy(), y)
    monkeypatch.setenv("LGBM_TPU_INGEST_KERNEL", "kernel")
    monkeypatch.setattr(ING, "parity_probe", lambda *a, **k: False)
    with pytest.warns(UserWarning, match="demoted"):
        dev = _dataset(X.copy(), y)
    assert np.array_equal(dev.binned, host.binned)
    assert dev._ingest == {}                  # cached demotion
    story = ING.ingest_last()
    assert story.get("path") == "host"
    assert "parity" in story.get("reason", "")


def test_kernel_exception_falls_back_cleanly(monkeypatch):
    """Any kernel exception mid-run re-zeroes the output and the host
    oracle produces the exact host bytes."""
    X, y = _raw()
    host = _dataset(X.copy(), y)
    monkeypatch.setenv("LGBM_TPU_INGEST_KERNEL", "kernel")

    def boom(self, X):
        raise RuntimeError("backend lost")
    monkeypatch.setattr(ING.DeviceBinner, "__call__", boom)
    with pytest.warns(UserWarning, match="demoted"):
        dev = _dataset(X.copy(), y)
    assert np.array_equal(dev.binned, host.binned)
    assert "RuntimeError" in ING.ingest_last().get("reason", "")


def test_int32_overflow_categorical_unsupported():
    """Category codes outside int32 cannot ride the device tables."""
    rng = np.random.RandomState(0)
    X = rng.rand(500, 3).astype(np.float64) * 10
    X[:, 0] = rng.choice([0.0, 1.0, 3.0e9], size=500)
    ds = _dataset(X, (rng.rand(500) > 0.5).astype(np.float64))
    with pytest.raises(ING.IngestUnsupported):
        ING.build_ingest_tables(ds)


# ---------------------------------------------------------------------
# directed rounding
# ---------------------------------------------------------------------

def test_round_bounds_f32_is_largest_f32_below():
    rng = np.random.RandomState(1)
    ub = np.concatenate([
        rng.randn(500) * 1e3, rng.randn(500) * 1e-3,
        [0.0, -0.0, 1e300, -1e300, np.inf, -np.inf]])
    r = ING.round_bounds_f32(ub)
    assert r.dtype == np.float32
    assert np.all(r.astype(np.float64) <= ub)          # never above
    with np.errstate(over="ignore"):
        up = np.nextafter(r, np.float32(np.inf)).astype(np.float64)
    finite = np.isfinite(ub)
    assert np.all(up[finite] > ub[finite])             # largest such f32
    assert np.isposinf(r[np.isposinf(ub)]).all()
    assert np.isneginf(r[np.isneginf(ub)]).all()


# ---------------------------------------------------------------------
# the pump
# ---------------------------------------------------------------------

def test_ingest_pump_pinned_ascending_order():
    """Resume safety: chunks arrive in index order with exact slices,
    ragged tail included, prefetched or not."""
    X = np.arange(1037 * 3, dtype=np.float32).reshape(1037, 3)
    for prefetch in (True, False):
        seen = []
        for i, start, rows, chunk in IngestPump(X, 100,
                                                prefetch=prefetch):
            seen.append(i)
            assert start == i * 100
            assert np.array_equal(np.asarray(chunk),
                                  X[start:start + rows])
        assert seen == list(range(11))


def test_ingest_pump_reader_error_surfaces():
    class Bad:
        shape = (500, 2)

        def __getitem__(self, sl):
            raise ValueError("torn source")
    with pytest.raises(ValueError, match="torn source"):
        for _ in IngestPump(Bad(), 100):
            pass


# ---------------------------------------------------------------------
# planner election
# ---------------------------------------------------------------------

def test_chunk_election_under_tight_ledger(monkeypatch):
    monkeypatch.delenv("LGBM_TPU_INGEST_CHUNK", raising=False)
    monkeypatch.delenv("LGBM_TPU_INGEST_KERNEL", raising=False)
    tight = P.ResidencyLedger(limit_bytes=64 << 20)
    roomy = P.ResidencyLedger(limit_bytes=16 * GB)
    kw = dict(rows=50_000_000, features=28, num_groups=28, item_bytes=1)
    small = P.plan_ingest(ledger=tight, **kw)
    big = P.plan_ingest(ledger=roomy, **kw)
    assert small.limit_source == "ledger"
    assert small.chunk_bytes <= small.budget_bytes
    assert small.chunk_rows < big.chunk_rows
    assert small.chunk_rows >= P.MIN_BUCKET_ROWS
    assert big.chunk_rows <= P.MAX_INGEST_CHUNK_ROWS
    # chunks are ladder rungs: stable autotune keys across nearby shapes
    assert small.chunk_rows == P.bucket_rows(small.chunk_rows)


def test_chunk_env_pin_wins(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_INGEST_CHUNK", "8192")
    plan = P.plan_ingest(rows=1_000_000, features=28, num_groups=28)
    assert plan.chunk_rows == 8192


def test_small_datasets_never_elect_chunks_past_rows(monkeypatch):
    monkeypatch.delenv("LGBM_TPU_INGEST_CHUNK", raising=False)
    plan = P.plan_ingest(rows=10_000, features=28, num_groups=28)
    assert plan.chunk_rows <= P.bucket_rows(10_000)


def test_variant_env_gate(monkeypatch):
    kw = dict(rows=1_000_000, features=28, num_groups=28)
    monkeypatch.setenv("LGBM_TPU_INGEST_KERNEL", "host")
    p1 = P.plan_ingest(**kw)
    assert (p1.variant, p1.elected_by) == ("host", "env")
    assert p1.tile_rows == 0
    monkeypatch.setenv("LGBM_TPU_INGEST_KERNEL", "kernel")
    p2 = P.plan_ingest(**kw)
    assert (p2.variant, p2.elected_by) == ("kernel", "env")
    assert p2.tile_rows in P.INGEST_TILES


def test_analytic_election_host_off_accelerator(monkeypatch):
    monkeypatch.delenv("LGBM_TPU_INGEST_KERNEL", raising=False)
    monkeypatch.setenv("LGBM_TPU_AUTOTUNE", "0")
    off = P.plan_ingest(rows=1_000_000, features=28, num_groups=28,
                        accel=False)
    assert (off.variant, off.elected_by) == ("host", "analytic")
    on = P.plan_ingest(rows=1_000_000, features=28, num_groups=28,
                       accel=True)
    assert (on.variant, on.elected_by) == ("kernel", "analytic")
    wide = P.plan_ingest(rows=1_000_000,
                         features=P.MAX_INGEST_KERNEL_FEATURES + 1,
                         num_groups=28, accel=True)
    assert wide.variant == "host"     # unrolled kernel stops paying


def test_measured_election_and_counters(monkeypatch, tmp_path):
    monkeypatch.setenv("LGBM_TPU_AUTOTUNE_DIR", str(tmp_path))
    monkeypatch.delenv("LGBM_TPU_INGEST_KERNEL", raising=False)
    kw = dict(rows=1_000_000, features=28, num_groups=28, item_bytes=1)
    P.autotune_counters(reset=True)
    cold = P.plan_ingest(accel=True, **kw)
    assert cold.measured_variant == ""
    assert cold.autotune_key.startswith("i-")
    P.record_ingest_timing(variant="host", seconds=0.01, **kw)
    P.record_ingest_timing(variant="kernel", seconds=0.5, **kw)
    warm = P.plan_ingest(accel=True, **kw)
    assert (warm.variant, warm.elected_by) == ("host", "measured")
    c = P.autotune_counters()
    assert c["hits"] >= 1 and c["misses"] >= 1 and c["flips"] >= 1
    # the stopwatch flips back when the kernel wins
    P.record_ingest_timing(variant="kernel", seconds=0.001, **kw)
    assert P.plan_ingest(accel=True, **kw).variant == "kernel"
    # unknown variant names in the store are skipped, not adopted
    P.record_ingest_timing(variant="warp9", seconds=1e-9, **kw)
    assert P.plan_ingest(accel=True, **kw).variant == "kernel"


def test_ingest_vmem_model_monotone():
    a = P.ingest_vmem_bytes(28, 256, 64, 1, 28)
    b = P.ingest_vmem_bytes(28, 2048, 64, 1, 28)
    assert 0 < a < b
    assert P.plan_ingest(rows=1_000_000, features=28, num_groups=28,
                         accel=True, vmem_bytes=1 << 10).variant == "host"
