"""Split search + tree grower vs brute-force NumPy reference."""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.dataset import FeatureMeta
from lightgbm_tpu.binning import BinMapper, MissingType
from lightgbm_tpu.grower import (GrowerConfig, grow_tree,
                                 predict_leaf_index_binned, predict_tree_binned)
from lightgbm_tpu.ops.histogram import build_histogram
from lightgbm_tpu.ops.split import SplitHyperparams, best_split_for_leaf


def _meta(num_bins, F):
    return FeatureMeta(
        num_bin=np.full(F, num_bins, np.int32),
        missing_type=np.zeros(F, np.int32),
        default_bin=np.zeros(F, np.int32),
        most_freq_bin=np.zeros(F, np.int32),
        is_categorical=np.zeros(F, bool),
        max_num_bin=num_bins,
    )


def brute_force_best_split(binned, grad, hess, hp: SplitHyperparams):
    """Exhaustive split search directly over rows (no histograms)."""
    n, F = binned.shape
    G, H = grad.sum(), hess.sum()
    parent_gain = G * G / (H + hp.lambda_l2 + 2e-15)
    best = (-np.inf, -1, -1)
    for f in range(F):
        for t in range(binned[:, f].max()):
            left = binned[:, f] <= t
            gl, hl = grad[left].sum(), hess[left].sum()
            gr, hr = G - gl, H - hl
            nl, nr = left.sum(), n - left.sum()
            if nl < hp.min_data_in_leaf or nr < hp.min_data_in_leaf:
                continue
            if hl < hp.min_sum_hessian_in_leaf or hr < hp.min_sum_hessian_in_leaf:
                continue
            gain = gl * gl / (hl + hp.lambda_l2 + 1e-15) + \
                gr * gr / (hr + hp.lambda_l2 + 1e-15)
            if gain > best[0] + 1e-9:
                best = (gain, f, t)
    return best


def test_best_split_matches_brute_force():
    rng = np.random.RandomState(0)
    n, F, B = 800, 5, 16
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = (rng.randn(n) + 0.3 * (binned[:, 2] > 7)).astype(np.float32)
    hess = np.ones(n, np.float32)
    hp = SplitHyperparams(min_data_in_leaf=20, min_sum_hessian_in_leaf=1e-3)

    hist = build_histogram(jnp.asarray(binned.T), jnp.asarray(grad),
                           jnp.asarray(hess), jnp.ones(n, jnp.float32), B,
                           method="scatter")
    meta = _meta(B, F)
    r = best_split_for_leaf(
        hist, jnp.float32(grad.sum()), jnp.float32(hess.sum()),
        jnp.float32(n), jnp.asarray(meta.num_bin), jnp.asarray(meta.missing_type),
        jnp.asarray(meta.default_bin), jnp.asarray(meta.is_categorical), hp)
    bf_gain, bf_f, bf_t = brute_force_best_split(binned, grad.astype(np.float64),
                                                 hess.astype(np.float64), hp)
    assert int(r.feature) == bf_f
    assert int(r.threshold) == bf_t
    parent_gain = grad.sum() ** 2 / (hess.sum() + 2e-15)
    np.testing.assert_allclose(float(r.gain), bf_gain - parent_gain, rtol=1e-3)


def test_min_data_in_leaf_enforced():
    rng = np.random.RandomState(1)
    n, F, B = 100, 3, 8
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=31, hp=SplitHyperparams(min_data_in_leaf=30),
                       num_bins=B, hist_method="scatter")
    tree, leaf_id = grow_tree(jnp.asarray(binned.T), jnp.asarray(grad),
                              jnp.asarray(hess), jnp.ones(n, jnp.float32),
                              meta, cfg)
    nl = int(tree.num_leaves)
    counts = np.asarray(tree.leaf_count[:nl])
    assert (counts >= 30).all()


def test_grower_leaf_ids_match_traversal():
    rng = np.random.RandomState(2)
    n, F, B = 600, 6, 32
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = (rng.randn(n) + (binned[:, 0] / B)).astype(np.float32)
    hess = np.ones(n, np.float32)
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=15, hp=SplitHyperparams(min_data_in_leaf=5),
                       num_bins=B, hist_method="scatter")
    tree, leaf_id = grow_tree(jnp.asarray(binned.T), jnp.asarray(grad),
                              jnp.asarray(hess), jnp.ones(n, jnp.float32),
                              meta, cfg)
    routed = predict_leaf_index_binned(tree, jnp.asarray(binned.T), meta)
    np.testing.assert_array_equal(np.asarray(leaf_id), np.asarray(routed))


def test_leaf_values_are_newton_steps():
    rng = np.random.RandomState(3)
    n, F, B = 500, 4, 16
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    meta = _meta(B, F)
    lam = 0.5
    cfg = GrowerConfig(num_leaves=8,
                       hp=SplitHyperparams(min_data_in_leaf=10, lambda_l2=lam),
                       num_bins=B, hist_method="scatter")
    tree, leaf_id = grow_tree(jnp.asarray(binned.T), jnp.asarray(grad),
                              jnp.asarray(hess), jnp.ones(n, jnp.float32),
                              meta, cfg)
    lid = np.asarray(leaf_id)
    nl = int(tree.num_leaves)
    for l in range(nl):
        rows = lid == l
        if rows.sum() == 0:
            continue
        expect = -grad[rows].sum() / (hess[rows].sum() + lam)
        np.testing.assert_allclose(float(tree.leaf_value[l]), expect,
                                   rtol=2e-3, atol=2e-4)


def test_max_depth_limit():
    rng = np.random.RandomState(4)
    n, F, B = 500, 5, 16
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=31, max_depth=2,
                       hp=SplitHyperparams(min_data_in_leaf=1),
                       num_bins=B, hist_method="scatter")
    tree, _ = grow_tree(jnp.asarray(binned.T), jnp.asarray(grad),
                        jnp.asarray(hess), jnp.ones(n, jnp.float32), meta, cfg)
    assert int(tree.num_leaves) <= 4
    assert int(np.asarray(tree.leaf_depth)[:int(tree.num_leaves)].max()) <= 2


def test_predict_tree_binned_values():
    rng = np.random.RandomState(5)
    n, F, B = 300, 3, 8
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=6, hp=SplitHyperparams(min_data_in_leaf=10),
                       num_bins=B, hist_method="scatter")
    tree, leaf_id = grow_tree(jnp.asarray(binned.T), jnp.asarray(grad),
                              jnp.asarray(hess), jnp.ones(n, jnp.float32),
                              meta, cfg)
    vals = np.asarray(predict_tree_binned(tree, jnp.asarray(binned.T), meta))
    lv = np.asarray(tree.leaf_value)
    np.testing.assert_allclose(vals, lv[np.asarray(leaf_id)], rtol=1e-6)
