"""Quantized-gradient training (use_quantized_grad): integer histogram
pipeline, default-mode byte parity, quality, payload accounting.

The hard contracts:

- DEFAULT MODE IS UNTOUCHED: with use_quantized_grad=false the trained
  model files are byte-identical to the pre-quantization codebase
  (goldens recorded from the commit before this feature merged);
- quantized training reaches f32-comparable quality on the synthetic
  suite (the NeurIPS'22 quantized-GBDT result this reproduces);
- the integer kernels agree with each other exactly (int sums have no
  accumulation-order wobble) and the sibling subtraction is exact;
- the data-parallel psum payload accounting matches the dtypes actually
  psum'd (int16 narrowing engages at the static bound).
"""

import hashlib
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

HERE = os.path.dirname(os.path.abspath(__file__))

RNG = np.random.RandomState(7)
N, F = 1200, 10
X = RNG.randn(N, F)
Y_BIN = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.2 * RNG.randn(N) > 0).astype(float)
Y_MC = np.digitize(X[:, 0] + X[:, 1], [-0.5, 0.5]).astype(float)

GOLDEN_CASES = {
    "gbdt": ({"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.1}, "bin"),
    "bagging": ({"objective": "binary", "num_leaves": 15,
                 "learning_rate": 0.1, "bagging_fraction": 0.7,
                 "bagging_freq": 2, "bagging_seed": 11}, "bin"),
    "goss": ({"objective": "binary", "boosting": "goss", "num_leaves": 15,
              "learning_rate": 0.2}, "bin"),
    "rf": ({"objective": "binary", "boosting": "rf", "num_leaves": 15,
            "bagging_fraction": 0.6, "bagging_freq": 1}, "bin"),
    "multiclass": ({"objective": "multiclass", "num_class": 3,
                    "num_leaves": 7, "learning_rate": 0.1}, "mc"),
}


def _train(params, y, rounds=10, n_rows=None, extra=None):
    p = dict(params)
    p.setdefault("verbosity", -1)
    p.update(extra or {})
    Xt = X if n_rows is None else X[:n_rows]
    yt = y if n_rows is None else y[:n_rows]
    ds = lgb.Dataset(Xt, label=yt, free_raw_data=False)
    return lgb.train(p, ds, num_boost_round=rounds, verbose_eval=False)


# ---------------------------------------------------------------- parity


@pytest.mark.parametrize("case", sorted(GOLDEN_CASES))
def test_default_mode_byte_identical_to_pre_quant_golden(case):
    """Parity guard: with use_quantized_grad absent, training output is
    byte-identical to the recorded pre-quantization goldens (sha256 of
    model text, generated at the commit before the integer pipeline
    merged) across gbdt/bagging/GOSS/RF/multiclass."""
    golden = json.load(open(os.path.join(HERE, "golden",
                                         "default_mode_sha256.json")))
    params, kind = GOLDEN_CASES[case]
    y = Y_MC if kind == "mc" else Y_BIN
    bst = _train(params, y)
    h = hashlib.sha256(bst.model_to_string().encode()).hexdigest()
    assert h == golden[case], (
        f"{case}: default-mode model drifted from the pre-quantization "
        "golden — the quantized code must be inert when disabled")


def _trees_only(model_text):
    """Model text minus the echoed parameters section (which faithfully
    records whatever keys the caller passed, quantization flags included)."""
    return model_text.split("\nparameters:")[0]


def test_quant_off_flag_matches_absent():
    """use_quantized_grad=false must train the identical model as the key
    being absent (only the echoed parameters section may differ)."""
    a = _train({"objective": "binary", "num_leaves": 15}, Y_BIN)
    b = _train({"objective": "binary", "num_leaves": 15,
                "use_quantized_grad": False}, Y_BIN)
    assert _trees_only(a.model_to_string()) == _trees_only(b.model_to_string())


def test_quant_mode_changes_models():
    a = _train({"objective": "binary", "num_leaves": 15}, Y_BIN)
    b = _train({"objective": "binary", "num_leaves": 15,
                "use_quantized_grad": True}, Y_BIN)
    assert a.model_to_string() != b.model_to_string()


def test_quant_deterministic_rerun():
    """Same config + seeds -> byte-identical quantized models (the
    stochastic rounding draws from the per-round key stream)."""
    p = {"objective": "binary", "num_leaves": 15, "use_quantized_grad": True}
    assert _train(p, Y_BIN, rounds=6).model_to_string() == \
        _train(p, Y_BIN, rounds=6).model_to_string()


# ---------------------------------------------------------------- quality


def _auc(y, p):
    o = np.argsort(p)
    r = np.empty_like(o, dtype=float)
    r[o] = np.arange(1, len(p) + 1)
    npos = y.sum()
    return (r[y > 0].sum() - npos * (npos + 1) / 2) / (npos * (len(y) - npos))


QRNG = np.random.RandomState(3)
NQ = 3000
XQ = QRNG.randn(NQ, F)
YQ = (XQ[:, 0] + 0.6 * XQ[:, 1] * XQ[:, 2]
      + 0.4 * QRNG.randn(NQ) > 0).astype(float)
XH = QRNG.randn(1500, F)
YH = (XH[:, 0] + 0.6 * XH[:, 1] * XH[:, 2]
      + 0.4 * QRNG.randn(1500) > 0).astype(float)


def _quality_pair(base, y, extra, rounds=20):
    f32 = lgb.train(dict(base), lgb.Dataset(XQ, label=y,
                                            free_raw_data=False),
                    rounds, verbose_eval=False)
    qnt = lgb.train(dict(base, use_quantized_grad=True, **extra),
                    lgb.Dataset(XQ, label=y, free_raw_data=False),
                    rounds, verbose_eval=False)
    return f32, qnt


# the two non-default variants ride the slow marker: tier-1 keeps one
# binary + one multiclass quality gate, the full suite sweeps the matrix
@pytest.mark.parametrize("extra", [
    {},                                     # defaults: 4 bins, stochastic
    pytest.param({"quant_train_renew_leaf": True},
                 marks=pytest.mark.slow),   # true-f32 leaf renewal
    pytest.param({"num_grad_quant_bins": 16, "stochastic_rounding": False},
                 marks=pytest.mark.slow),
])
def test_quant_quality_binary(extra):
    """Quantized AUC within tolerance of f32 on synthetic binary."""
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    f32, qnt = _quality_pair(base, YQ, extra)
    a_f = _auc(YH, f32.predict(XH))
    a_q = _auc(YH, qnt.predict(XH))
    assert a_f > 0.9, a_f                 # the suite is learnable at all
    assert a_q > a_f - 0.015, (a_f, a_q, extra)


def test_quant_quality_multiclass():
    ym = np.digitize(XQ[:, 0] + XQ[:, 1], [-0.6, 0.6]).astype(float)
    ymh = np.digitize(XH[:, 0] + XH[:, 1], [-0.6, 0.6]).astype(float)

    def logloss(y, p):
        p = np.clip(p.reshape(-1, 3), 1e-15, 1.0)
        return -np.mean(np.log(p[np.arange(len(y)), y.astype(int)]))

    base = {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
            "verbosity": -1}
    f32, qnt = _quality_pair(base, ym, {}, rounds=15)
    ll_f = logloss(ymh, f32.predict(XH))
    ll_q = logloss(ymh, qnt.predict(XH))
    assert ll_q < ll_f * 1.10 + 0.01, (ll_f, ll_q)


# ----------------------------------------------------------- fallback


@pytest.mark.parametrize("params,blocker", [
    ({"objective": "regression",
      "monotone_constraints": [1, -1] + [0] * (F - 2)},
     "monotone_constraints"),
    ({"objective": "binary", "extra_trees": True}, "extra_trees"),
    ({"objective": "binary", "cegb_penalty_split": 0.1}, "CEGB"),
    ({"objective": "binary", "boosting": "dart"}, "boosting=dart"),
])
def test_quant_fallback_warns_and_trains_f32(params, blocker, capsys):
    y = Y_BIN if params["objective"] == "binary" else Y_BIN
    p = dict(params, num_leaves=15, use_quantized_grad=True, verbosity=1)
    bst = _train(p, y, rounds=3)
    assert bst.num_trees() >= 3
    assert bst.boosting._quant_on is False
    cap = capsys.readouterr()
    out = cap.out + cap.err
    assert "use_quantized_grad" in out and blocker in out
    # ...and the fallback output equals plain f32 training byte-for-byte
    # (modulo the echoed parameters section, which records the flags)
    p2 = dict(params, num_leaves=15, verbosity=-1)
    assert _trees_only(bst.model_to_string()) == \
        _trees_only(_train(p2, y, rounds=3).model_to_string())


def test_quant_bins_validation():
    with pytest.raises(Exception, match="num_grad_quant_bins"):
        _train({"objective": "binary", "use_quantized_grad": True,
                "num_grad_quant_bins": 256}, Y_BIN, rounds=1)


def test_quant_aliases():
    from lightgbm_tpu.config import Config
    cfg = Config.from_params({"quantized_grad": True, "grad_quant_bins": 8})
    assert cfg.use_quantized_grad is True
    assert cfg.num_grad_quant_bins == 8


# ----------------------------------------------------------- kernels


def _synth_hist_inputs(n=4096, f=6, B=32, bins=8, seed=0):
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import quantize_gradients
    import jax
    rng = np.random.RandomState(seed)
    binned_t = jnp.asarray(rng.randint(0, B - 1, (f, n)), jnp.uint8)
    grad = jnp.asarray(rng.randn(n), jnp.float32)
    hess = jnp.abs(jnp.asarray(rng.randn(n), jnp.float32)) + 0.1
    w = jnp.asarray((rng.rand(n) > 0.2).astype(np.float32))
    gq, hq, gs, hs = quantize_gradients(grad, hess, w, bins,
                                        jax.random.PRNGKey(1))
    return binned_t, gq, hq, w, gs, hs


def test_int_kernels_agree_exactly():
    """matmul_int8 and scatter_int produce IDENTICAL int32 histograms
    (no accumulation-order tolerance needed — that is the point)."""
    from lightgbm_tpu.ops.histogram import build_histogram_int, quant_levels
    binned_t, gq, hq, w, _, _ = _synth_hist_inputs()
    B, bins = 32, 8
    hm = build_histogram_int(binned_t, gq, hq, w > 0, B,
                             method="matmul_int8")
    hs_ = build_histogram_int(binned_t, gq, hq, w > 0, B,
                              method="scatter_int",
                              levels=quant_levels(bins))
    assert hm.dtype == np.int32
    np.testing.assert_array_equal(np.asarray(hm), np.asarray(hs_))


def test_int_histogram_matches_quantized_reference():
    """The integer histogram equals a plain numpy accumulation of the
    quantized values — and rescaling tracks the f32 histogram."""
    from lightgbm_tpu.ops.histogram import build_histogram_int
    binned_t, gq, hq, w, gs, hs = _synth_hist_inputs()
    B = 32
    hist = np.asarray(build_histogram_int(binned_t, gq, hq, w > 0, B,
                                          method="matmul_int8"))
    bt = np.asarray(binned_t)
    gqn = np.asarray(gq, np.int64)
    hqn = np.asarray(hq, np.int64)
    member = np.asarray(w) > 0
    for f_i in range(bt.shape[0]):
        ref_g = np.bincount(bt[f_i][member], weights=gqn[member],
                            minlength=B)
        ref_h = np.bincount(bt[f_i][member], weights=hqn[member],
                            minlength=B)
        np.testing.assert_array_equal(hist[0, f_i], ref_g)
        np.testing.assert_array_equal(hist[1, f_i], ref_h)


def test_int_subtraction_exact():
    """Sibling trick in integer domain: parent - child == independently
    built sibling, EXACTLY (the f32 path can only claim this to rounding)."""
    from lightgbm_tpu.ops.histogram import build_histogram_int
    import jax.numpy as jnp
    binned_t, gq, hq, w, _, _ = _synth_hist_inputs()
    B = 32
    n = binned_t.shape[1]
    left = jnp.asarray(np.random.RandomState(5).rand(n) < 0.37)
    member = w > 0
    parent = build_histogram_int(binned_t, gq, hq, member, B,
                                 method="matmul_int8")
    child = build_histogram_int(binned_t, gq, hq, member & left, B,
                                method="matmul_int8")
    sib = build_histogram_int(binned_t, gq, hq, member & ~left, B,
                              method="matmul_int8")
    np.testing.assert_array_equal(np.asarray(parent - child),
                                  np.asarray(sib))


def test_segment_int_kernels_agree():
    """Scatter, sorted-arena and slot-expanded integer segment kernels
    produce identical [S, 2, F, B] histograms."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import (quant_levels,
                                            segment_histogram_expanded_int,
                                            segment_histogram_int,
                                            segment_histogram_sorted_int)
    binned_t, gq, hq, w, _, _ = _synth_hist_inputs()
    B, S = 32, 5
    n = binned_t.shape[1]
    slot = jnp.asarray(np.random.RandomState(9).randint(0, S + 1, n))
    member = w > 0
    ref = np.asarray(segment_histogram_int(binned_t, gq, hq, member, slot,
                                           S, B, levels=quant_levels(8)))
    slot_w = jnp.where(member, slot, S)
    srt = np.asarray(segment_histogram_sorted_int(binned_t, gq, hq, slot_w,
                                                  S, B))
    np.testing.assert_array_equal(ref, srt)
    exp = np.asarray(segment_histogram_expanded_int(binned_t, gq, hq,
                                                    member, slot, B,
                                                    live_cap=S))
    np.testing.assert_array_equal(ref, exp)


def test_quantize_gradients_properties():
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import quantize_gradients
    rng = np.random.RandomState(0)
    g = jnp.asarray(rng.randn(5000), jnp.float32)
    h = jnp.abs(jnp.asarray(rng.randn(5000), jnp.float32))
    w = jnp.asarray((rng.rand(5000) > 0.3).astype(np.float32)) * 2.0
    gq, hq, gs, hs = quantize_gradients(g, h, w, 8, jax.random.PRNGKey(0))
    gqn, hqn = np.asarray(gq, np.int64), np.asarray(hq, np.int64)
    assert gqn.min() >= -3 and gqn.max() <= 3      # bins//2 - 1 = 3
    assert hqn.min() >= 0 and hqn.max() <= 7       # bins - 1
    wn = np.asarray(w)
    assert (gqn[wn == 0] == 0).all() and (hqn[wn == 0] == 0).all()
    # stochastic rounding is unbiased: the rescaled sums track the
    # weighted f32 sums within a few-sigma CLT band
    gw = np.asarray(g) * wn
    err = abs(float(gqn.sum()) * float(gs) - gw.sum())
    assert err < 6.0 * float(gs) * np.sqrt(5000), err


def test_quant_psum_payload_accounting():
    from lightgbm_tpu.ops.histogram import (hist_payload_bytes,
                                            quant_psum_narrow)
    # f32: 3 channels x 4 bytes
    assert hist_payload_bytes(28, 64) == 3 * 28 * 64 * 4
    # int32 channels at HIGGS scale (bound exceeds int16)
    assert hist_payload_bytes(28, 64, 11_000_000, 4) == 2 * 28 * 64 * 4
    # int16 narrowing at small bound: rows * (bins-1) < 2^15
    assert quant_psum_narrow(1200, 4)
    assert not quant_psum_narrow(11_000_000, 4)
    assert hist_payload_bytes(28, 64, 1200, 4) == 2 * 28 * 64 * 2
    # payload always shrinks vs f32
    assert hist_payload_bytes(28, 64, 11_000_000, 4) < \
        hist_payload_bytes(28, 64)


def test_resolve_hist_method_quant(monkeypatch):
    from lightgbm_tpu.ops import histogram as H
    assert H.resolve_hist_method("auto", quantized=True) == "scatter_int"
    monkeypatch.setattr(H, "on_accelerator", lambda: True)
    # int32-accumulation matmul kernel selected on accelerator
    assert H.resolve_hist_method("auto", quantized=True) == "matmul_int8"
    assert H.resolve_hist_method("matmul", quantized=True) == "matmul_int8"
    assert H.resolve_hist_method("scatter", quantized=True) == "scatter_int"


# ----------------------------------------------------------- state


def test_quant_scales_in_checkpoint_state():
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "use_quantized_grad": True}
    ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
    bst = lgb.Booster(params=p, train_set=ds)
    bst.update()
    st = bst.boosting.capture_state()
    qs = st["quant_scales"]
    assert qs is not None and qs.shape == (1, 2) and (qs > 0).all()


def test_quant_checkpoint_resume_bit_parity(tmp_path):
    """Mid-stream checkpoint resume reproduces the byte-identical
    quantized model (the SR key streams replay by absolute iteration)."""
    snap = str(tmp_path / "m.txt")
    P = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "use_quantized_grad": True, "bagging_fraction": 0.7,
         "bagging_freq": 1}

    def run(resume=None):
        ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
        return lgb.train(P, ds, 9, verbose_eval=False, snapshot_freq=4,
                         snapshot_out=snap,
                         resume_from=resume).model_to_string()

    full = run()
    assert run(resume=snap + ".ckpt") == full


def test_quant_sharded_data_parallel():
    """Quantized training over the 8-device mesh: integer histogram
    psums (int16-narrowed at this scale), chunked == per-iteration."""
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")

    def train(chunks):
        p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "use_quantized_grad": True, "tree_learner": "data"}
        ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
        b = lgb.Booster(params=p, train_set=ds)
        for c in chunks:
            b.update_chunk(c) if c > 1 else b.update()
        return b.model_to_string()

    assert train([4, 2]) == train([1] * 6)


def test_quant_voting_parallel():
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "use_quantized_grad": True, "tree_learner": "voting", "top_k": 5}
    ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
    bst = lgb.train(p, ds, 4, verbose_eval=False)
    pred = bst.predict(X)
    assert np.isfinite(pred).all()
    assert _auc(Y_BIN, pred) > 0.75


def test_quant_rounds_grower_sorted_arena(monkeypatch):
    """The accelerator-shaped rounds grower path (sorted int arena +
    expanded int pass + quant packed records) trains on CPU via the
    LGBM_TPU_SEGHIST=sorted override."""
    monkeypatch.setenv("LGBM_TPU_SEGHIST", "sorted")
    p = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
         "use_quantized_grad": True, "tpu_tree_growth": "rounds"}
    ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
    bst = lgb.train(p, ds, 4, verbose_eval=False)
    pred = bst.predict(X)
    assert np.isfinite(pred).all()
    assert _auc(Y_BIN, pred) > 0.8


# ----------------------------------------------------------- probe


def test_hist_probe_json():
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))
    from hist_probe import run_probe
    out = run_probe(rows=20000, features=8, max_bin=31, quant_bins=4,
                    leaves=31, reps=1)
    assert out["quant_method"] in ("matmul_int8", "scatter_int")
    assert out["f32"]["ms_per_pass"] > 0
    assert out["quant"]["ms_per_pass"] > 0
    # the headline claim: quantized histogram psum payload is smaller
    assert out["quant"]["psum_payload_bytes"] < \
        out["f32"]["psum_payload_bytes"]
    assert out["payload_shrink"] > 1.0
    assert out["rescale_abs_err"]["ok"]


def test_compacted_int_caps_ladder():
    """The bucketed-capacity integer gather path (lax.switch over the
    static cap ladder) matches the full masked pass for a sparse member
    set — training only reaches the ladder above ~16k rows, so cover the
    switch branches directly."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import (build_histogram_int,
                                            compacted_histogram_int,
                                            quant_levels)
    binned_t, gq, hq, w, _, _ = _synth_hist_inputs(n=6000)
    B = 32
    n = binned_t.shape[1]
    member = jnp.asarray(np.random.RandomState(2).rand(n) < 0.05)
    caps = [8192, 2048, 512]
    got = np.asarray(compacted_histogram_int(
        binned_t, gq, hq, w, member, B, caps, method="scatter_int",
        levels=quant_levels(8)))
    want = np.asarray(build_histogram_int(
        binned_t, gq, hq, member & (w > 0), B, method="scatter_int",
        levels=quant_levels(8)))
    np.testing.assert_array_equal(got, want)
