"""Fast-prediction paths: stacked forest, native lib, device forest,
early stop, CSR, batched SHAP.

reference analogues: src/application/predictor.hpp (row-parallel predictor),
src/boosting/prediction_early_stop.cpp, c_api.h:698 (CSR predict).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.predict import StackedForest

EXAMPLES = "/root/reference/examples"


def _load(path):
    d = np.loadtxt(path)
    return d[:, 1:], d[:, 0]


@pytest.fixture(scope="module")
def binary_model():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    bst = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 31},
                    lgb.Dataset(X, label=y), num_boost_round=20,
                    verbose_eval=False)
    Xt, yt = _load(f"{EXAMPLES}/binary_classification/binary.test")
    return bst, Xt


@pytest.fixture(scope="module")
def nan_cat_model():
    rng = np.random.RandomState(3)
    n = 2000
    cat = rng.randint(0, 12, n).astype(np.float64)
    other = rng.randn(n)
    other[rng.rand(n) < 0.25] = np.nan
    y = (np.isin(cat, [1, 4, 9]).astype(float) + 0.3 * np.nan_to_num(other)
         > 0.5).astype(float)
    X = np.column_stack([cat, other])
    bst = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 7,
                     "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y, categorical_feature=[0]),
                    num_boost_round=15, verbose_eval=False)
    return bst, X


def _per_tree_raw(bst, X):
    out = np.zeros(len(X))
    for m in bst.models:
        out += m.predict_np(X)
    return out


def test_forest_matches_per_tree(binary_model):
    bst, Xt = binary_model
    np.testing.assert_allclose(bst.predict(Xt, raw_score=True),
                               _per_tree_raw(bst, Xt), rtol=0, atol=0)


def test_forest_matches_per_tree_nan_cat(nan_cat_model):
    bst, X = nan_cat_model
    np.testing.assert_allclose(bst.predict(X, raw_score=True),
                               _per_tree_raw(bst, X), rtol=0, atol=0)


def test_numpy_fallback_matches_native(binary_model):
    bst, Xt = binary_model
    native = bst.predict(Xt, raw_score=True)
    forest = bst._forest(0, 20)
    if forest._native() is None:
        pytest.skip("native lib unavailable")
    forest._native_lib = None
    try:
        fallback = bst.predict(Xt, raw_score=True)
    finally:
        del forest._native_lib  # re-probe on next use
    np.testing.assert_allclose(native, fallback, rtol=0, atol=0)


def test_pred_leaf_layout(binary_model):
    bst, Xt = binary_model
    leaves = bst.predict(Xt, pred_leaf=True)
    assert leaves.shape == (len(Xt), 20)
    per_tree = np.column_stack([m.predict_leaf_np(Xt) for m in bst.models])
    np.testing.assert_array_equal(leaves, per_tree)


def test_device_forest(binary_model):
    bst, Xt = binary_model
    host = bst.predict(Xt, raw_score=True)
    dev = bst.predict(Xt, raw_score=True, device=True)
    # f32 accumulation: equal routing, tiny value drift
    np.testing.assert_allclose(dev, host, rtol=0, atol=1e-5)
    np.testing.assert_array_equal(bst.predict(Xt, pred_leaf=True, device=True),
                                  bst.predict(Xt, pred_leaf=True))


def test_early_stop_binary(binary_model):
    bst, Xt = binary_model
    full = bst.predict(Xt)
    es = bst.predict(Xt, pred_early_stop=True, pred_early_stop_freq=5,
                     pred_early_stop_margin=10.0)
    # margin 10 is the reference default and effectively never fires here
    np.testing.assert_allclose(es, full, rtol=0, atol=0)
    es_tight = bst.predict(Xt, pred_early_stop=True, pred_early_stop_freq=2,
                           pred_early_stop_margin=0.5)
    # the stop must actually fire (scores frozen early) ...
    assert np.abs(es_tight - full).max() > 0
    # ... while decisions agree for confident rows (measured 0.992)
    agree = ((es_tight > 0.5) == (full > 0.5)).mean()
    assert agree > 0.95


def test_early_stop_multiclass():
    X, y = _load(f"{EXAMPLES}/multiclass_classification/multiclass.train")
    bst = lgb.train({"objective": "multiclass", "num_class": 5,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=10, verbose_eval=False)
    full = bst.predict(X)
    es = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=3,
                     pred_early_stop_margin=10.0)
    np.testing.assert_allclose(es, full, rtol=0, atol=0)


def test_csr_predict_no_densify(binary_model):
    scipy_sparse = pytest.importorskip("scipy.sparse")
    bst, Xt = binary_model
    sp = scipy_sparse.csr_matrix(Xt)
    np.testing.assert_allclose(bst.predict(sp), bst.predict(Xt),
                               rtol=0, atol=0)
    # leaf + contrib shapes survive the chunked path
    assert bst.predict(sp, pred_leaf=True).shape == (len(Xt), 20)


def test_batched_shap_matches_scalar(nan_cat_model):
    bst, X = nan_cat_model
    sub = X[:40]
    F = X.shape[1]
    batched = bst.predict(sub, pred_contrib=True)
    scalar = np.zeros((len(sub), F + 1))
    for m in bst.models:
        scalar += m.predict_contrib_np(sub, F)
    np.testing.assert_allclose(batched, scalar, rtol=1e-9, atol=1e-12)
    # SHAP sums to raw prediction
    np.testing.assert_allclose(batched.sum(axis=1),
                               bst.predict(sub, raw_score=True),
                               rtol=1e-9, atol=1e-10)


def test_single_leaf_trees_predict():
    # the stacked forest's sentinel node must route every row of a
    # single-leaf (constant) tree to leaf 0, on all three backends
    from lightgbm_tpu.tree import HostTree
    forest = StackedForest([HostTree.constant(2.5), HostTree.constant(-1.0)])
    X = np.random.RandomState(0).rand(64, 3)
    np.testing.assert_allclose(forest.predict_raw(X)[0], 1.5, rtol=0)
    forest._native_lib = None   # numpy fallback
    np.testing.assert_allclose(forest.predict_raw(X)[0], 1.5, rtol=0)
    from lightgbm_tpu.predict import DeviceForest
    np.testing.assert_allclose(
        DeviceForest(forest, chunk_rows=64).predict_raw(X)[0], 1.5, rtol=1e-6)
