"""CEGB and forced-splits tests.

reference semantics:
- CEGB: src/treelearner/cost_effective_gradient_boosting.hpp (DetlaGain :50,
  UpdateLeafBestSplits :63, CalculateOndemandCosts :93) with hooks at
  serial_tree_learner.cpp:65-68,529-532,680-684.
- Forced splits: SerialTreeLearner::ForceSplits BFS
  (serial_tree_learner.cpp:411-521), forcedsplits_filename config.
"""
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=600, f=5, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (2.0 * X[:, 0] + 1.0 * X[:, 1] + 0.5 * X[:, 2]
         + 0.05 * rng.randn(n)).astype(np.float32)
    return X, y


BASE = {"objective": "regression", "num_leaves": 16, "verbosity": -1,
        "min_data_in_leaf": 5, "learning_rate": 0.1}


def _total_leaves(booster):
    return sum(m.num_leaves for m in booster.boosting.models)


def _used_features(booster):
    out = set()
    for m in booster.boosting.models:
        for s in range(m.num_leaves - 1):
            out.add(int(m.split_feature[s]))
    return out


class TestCEGB:
    def test_split_penalty_prunes(self):
        """cegb_penalty_split * num_data_in_leaf is subtracted from every
        candidate gain (DetlaGain), so a positive penalty must strictly
        reduce tree size and a huge one must stop growth entirely."""
        X, y = _data()
        b0 = lgb.train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=2)
        b1 = lgb.train(dict(BASE, cegb_penalty_split=0.05),
                       lgb.Dataset(X, label=y), num_boost_round=2)
        b2 = lgb.train(dict(BASE, cegb_penalty_split=100.0),
                       lgb.Dataset(X, label=y), num_boost_round=2)
        assert 0 < _total_leaves(b1) < _total_leaves(b0)
        # nothing beats the penalty: no splits (the first-iteration stump
        # is kept as a constant tree, reference AsConstantTree semantics)
        assert sum(m.num_leaves - 1 for m in b2.boosting.models) == 0

    def test_split_penalty_changes_chosen_splits(self):
        X, y = _data()
        b0 = lgb.train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=1)
        b1 = lgb.train(dict(BASE, cegb_penalty_split=0.05),
                       lgb.Dataset(X, label=y), num_boost_round=1)
        t0 = b0.boosting.models[0]
        t1 = b1.boosting.models[0]
        assert (t0.num_leaves != t1.num_leaves
                or t0.split_feature[:t0.num_leaves - 1].tolist()
                != t1.split_feature[:t1.num_leaves - 1].tolist())

    def test_coupled_penalty_concentrates_features(self):
        """The coupled penalty applies only to features not yet used in any
        split; once paid it vanishes for the rest of training, so a large
        coupled penalty concentrates splits on few features."""
        X, y = _data()
        b0 = lgb.train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=3)
        b1 = lgb.train(dict(BASE, cegb_penalty_feature_coupled=[5.0] * 5),
                       lgb.Dataset(X, label=y), num_boost_round=3)
        assert len(_used_features(b1)) < len(_used_features(b0))
        assert _total_leaves(b1) > 0       # penalty paid once, growth continues

    def test_coupled_state_persists_across_trees(self):
        """is_feature_used_in_split_ persists across Train calls in the
        reference learner: a feature paid for in tree 1 is free in tree 2.
        With a penalty high enough to admit exactly one feature, later
        trees must keep using that same feature rather than stalling."""
        X, y = _data()
        b = lgb.train(dict(BASE, cegb_penalty_feature_coupled=[5.0] * 5),
                      lgb.Dataset(X, label=y), num_boost_round=4)
        assert len(b.boosting.models) == 4
        per_tree_feats = [
            {int(f) for f in m.split_feature[:m.num_leaves - 1]}
            for m in b.boosting.models if m.num_leaves > 1]
        # every later tree reuses already-paid features only
        paid = per_tree_feats[0]
        for feats in per_tree_feats[1:]:
            assert feats <= paid
            paid |= feats

    def test_lazy_penalty_prunes(self):
        X, y = _data()
        b0 = lgb.train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=2)
        b1 = lgb.train(dict(BASE, cegb_penalty_feature_lazy=[0.05] * 5),
                       lgb.Dataset(X, label=y), num_boost_round=2)
        b2 = lgb.train(dict(BASE, cegb_penalty_feature_lazy=[10.0] * 5),
                       lgb.Dataset(X, label=y), num_boost_round=2)
        assert _total_leaves(b1) <= _total_leaves(b0)
        assert sum(m.num_leaves - 1 for m in b2.boosting.models) == 0

    def test_penalty_list_length_validated(self):
        X, y = _data()
        with pytest.raises(ValueError, match="same size as feature number"):
            lgb.train(dict(BASE, cegb_penalty_feature_coupled=[1.0, 2.0]),
                      lgb.Dataset(X, label=y), num_boost_round=1)

    def test_tradeoff_scales_penalty(self):
        """cegb_tradeoff multiplies every penalty: tradeoff=0 with a split
        penalty must reproduce the unpenalized model exactly."""
        X, y = _data()
        b0 = lgb.train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=2)
        b1 = lgb.train(dict(BASE, cegb_penalty_split=0.05,
                            cegb_tradeoff=0.0),
                       lgb.Dataset(X, label=y), num_boost_round=2)
        np.testing.assert_allclose(b0.predict(X), b1.predict(X), rtol=1e-6)


class TestForcedSplits:
    def _forced_file(self, tmp_path, spec):
        fn = os.path.join(str(tmp_path), "forced.json")
        with open(fn, "w") as f:
            json.dump(spec, f)
        return fn

    def test_root_forced(self, tmp_path):
        X, y = _data()
        fn = self._forced_file(tmp_path, {"feature": 3, "threshold": 0.5})
        b = lgb.train(dict(BASE, forcedsplits_filename=fn),
                      lgb.Dataset(X, label=y), num_boost_round=1)
        t = b.boosting.models[0]
        assert int(t.split_feature[0]) == 3
        # threshold maps to the bin boundary containing 0.5
        assert abs(t.threshold[0] - 0.5) < 0.1

    def test_bfs_order_and_leaf_routing(self, tmp_path):
        """Left child keeps the parent's leaf index, right child of the
        i-th split gets leaf i+1 — the BFS plan must land its children on
        the correct leaves (reference ForceSplits queue order)."""
        X, y = _data()
        fn = self._forced_file(tmp_path, {
            "feature": 3, "threshold": 0.5,
            "left": {"feature": 4, "threshold": 0.25},
            "right": {"feature": 4, "threshold": 0.75},
        })
        b = lgb.train(dict(BASE, forcedsplits_filename=fn),
                      lgb.Dataset(X, label=y), num_boost_round=1)
        t = b.boosting.models[0]
        assert int(t.split_feature[0]) == 3
        assert int(t.split_feature[1]) == 4 and int(t.split_feature[2]) == 4
        thr = sorted([t.threshold[1], t.threshold[2]])
        assert abs(thr[0] - 0.25) < 0.1 and abs(thr[1] - 0.75) < 0.1
        # structure: node 1 must be the left child of node 0, node 2 the right
        assert t.left_child[0] == 1 and t.right_child[0] == 2

    def test_partition_consistency(self, tmp_path):
        """Rows route consistently with the forced thresholds: predictions
        on the two sides of the forced root split must differ by leaf."""
        X, y = _data()
        fn = self._forced_file(tmp_path, {"feature": 0, "threshold": 0.5})
        b = lgb.train(dict(BASE, forcedsplits_filename=fn),
                      lgb.Dataset(X, label=y), num_boost_round=1)
        t = b.boosting.models[0]
        leaves = b.predict(X, pred_leaf=True).astype(int).ravel()
        thr = float(t.threshold[0])
        # every row <= thr goes into the left subtree of node 0
        left_leaves = {int(l) for l in leaves[X[:, 0] <= thr]}
        right_leaves = {int(l) for l in leaves[X[:, 0] > thr]}
        assert left_leaves.isdisjoint(right_leaves)

    def test_training_continues_best_first(self, tmp_path):
        """After the plan is exhausted, growth continues gain-driven up to
        num_leaves (the forced tree must not be limited to the plan)."""
        X, y = _data()
        fn = self._forced_file(tmp_path, {"feature": 3, "threshold": 0.5})
        b = lgb.train(dict(BASE, forcedsplits_filename=fn),
                      lgb.Dataset(X, label=y), num_boost_round=1)
        b0 = lgb.train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=1)
        t = b.boosting.models[0]
        assert t.num_leaves > 2
        assert t.num_leaves == b0.boosting.models[0].num_leaves

    def test_bad_forced_split_aborts_plan(self, tmp_path):
        """A forced split with no positive gain (all rows on one side)
        abandons the rest of the plan; training continues best-first
        (reference: abort_last_forced_split)."""
        X, y = _data()
        fn = self._forced_file(tmp_path, {
            "feature": 3, "threshold": 100.0,      # all rows left
            "left": {"feature": 4, "threshold": 0.5},
        })
        b = lgb.train(dict(BASE, forcedsplits_filename=fn),
                      lgb.Dataset(X, label=y), num_boost_round=1)
        t = b.boosting.models[0]
        # the degenerate forced split must NOT be applied
        assert not (int(t.split_feature[0]) == 3 and t.threshold[0] > 1.0)
        assert t.num_leaves > 1            # best-first growth proceeded

    def test_forced_plus_accuracy(self, tmp_path):
        """Forcing a reasonable split must not destroy model quality."""
        X, y = _data(n=2000)
        fn = self._forced_file(tmp_path, {"feature": 0, "threshold": 0.5})
        b0 = lgb.train(dict(BASE), lgb.Dataset(X, label=y),
                       num_boost_round=20)
        b1 = lgb.train(dict(BASE, forcedsplits_filename=fn),
                       lgb.Dataset(X, label=y), num_boost_round=20)
        mse0 = float(np.mean((b0.predict(X) - y) ** 2))
        mse1 = float(np.mean((b1.predict(X) - y) ** 2))
        assert mse1 < mse0 * 1.5


def test_forced_exact_parity_stats_convention(tmp_path):
    """tpu_forced_split_parity reproduces the reference's
    GatherInfoForThreshold stats convention (bin == threshold accumulates
    RIGHT, feature_histogram.hpp:527), which is one bin off from the
    default self-consistent rule (bin <= threshold left).  With mass in
    the threshold bin the recorded left count must strictly shrink."""
    X, y = _data(800, 4)
    fn = os.path.join(str(tmp_path), "forced.json")
    with open(fn, "w") as f:
        json.dump({"feature": 0, "threshold": 0.5}, f)
    base = dict(BASE, num_leaves=2, forcedsplits_filename=fn)
    b_def = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=1)
    b_par = lgb.train(dict(base, tpu_forced_split_parity=True),
                      lgb.Dataset(X, label=y), num_boost_round=1)
    t_def, t_par = b_def.boosting.models[0], b_par.boosting.models[0]
    assert int(t_def.split_feature[0]) == 0
    assert int(t_par.split_feature[0]) == 0
    assert int(t_def.threshold_in_bin[0]) == int(t_par.threshold_in_bin[0])
    l_def, r_def = float(t_def.leaf_count[0]), float(t_def.leaf_count[1])
    l_par, r_par = float(t_par.leaf_count[0]), float(t_par.leaf_count[1])
    assert l_def + r_def == l_par + r_par == len(X)
    assert l_par < l_def            # threshold-bin mass moved to the right
