"""Seeded traced-purity violations: host effects inside jit code."""
import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_kernel(x, y):
    t = time.time()  # SEED traced-purity
    r = np.random.rand()  # SEED traced-purity
    e = os.environ.get("SOME_PLAIN_VAR")  # SEED traced-purity
    s = x.sum().item()  # SEED traced-purity
    h = float(y)  # SEED traced-purity
    a = np.asarray(x)  # SEED traced-purity
    if y > 0:  # SEED traced-purity
        x = x + 1
    return x + t + r + s + h + a.shape[0] + (0 if e else 1)


def build_step():
    def step(state, grad):
        now = time.perf_counter()  # SEED traced-purity
        if grad:  # SEED traced-purity
            state = state + grad
        return state + now

    return jax.jit(step, donate_argnums=(0,))


def build_partial():
    def fold(hist, rows, num_bins):
        # num_bins is partial-bound -> static: this branch is fine
        if num_bins > 16:
            hist = hist * 2
        return hist + rows

    return jax.jit(functools.partial(fold, num_bins=32))


@functools.partial(jax.jit, static_argnames=("training",))
def static_ok(x, training):
    # negative cases: static param branch, shape branch, is-comparison
    if training:
        x = x * 2
    if x is None:
        return x
    total = jnp.sum(x)
    return total
