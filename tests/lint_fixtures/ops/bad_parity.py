"""Seeded parity-hazard violations (fixture lives under ops/)."""
import jax.numpy as jnp
from jax import lax


def sloppy_dots(a, b, onehot):
    h1 = jnp.dot(a, onehot)  # SEED parity-hazard
    h2 = lax.dot_general(a, b, (((1,), (0,)), ((), ())))  # SEED parity-hazard
    h3 = a @ b  # SEED parity-hazard
    return h1 + h2 + h3


def pinned_dots(a, b, onehot):
    # negative cases: both blessed spellings
    h1 = lax.dot(a, onehot, preferred_element_type=jnp.int32)
    h2 = jnp.matmul(a, b, precision=lax.Precision.HIGHEST)
    return h1 + h2
