"""The bad_env/bad_write violations again, silenced by pragmas — this
file must lint completely clean (tests assert it)."""
# tpulint: disable-file=traced-purity
import os
import time

import jax


def read_unknown_flag():
    # justification: fixture exercising the line pragma
    return os.environ.get(
        "LGBM_TPU_FIXTURE_UNKNOWN")  # tpulint: disable=env-flag-registry


def raw_write(path, text):
    # justification: fixture exercising multi-rule line pragma
    with open(path, "w") as fh:  # tpulint: disable=atomic-write,env-flag-registry
        fh.write(text)


@jax.jit
def impure_but_filed(x):
    # silenced by the file-level pragma at the top
    return x + time.time()
