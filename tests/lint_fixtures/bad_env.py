"""Seeded env-flag-registry violations: unregistered flag literals."""
import os

_CONST_FLAG = "LGBM_TPU_FIXTURE_UNKNOWN"  # SEED env-flag-registry


def read_flags():
    a = os.environ.get("LIGHTGBM_TPU_FIXTURE_BOGUS")  # SEED env-flag-registry
    b = os.getenv("BENCH_FIXTURE_NOT_REGISTERED", "0")  # SEED env-flag-registry
    c = os.environ.get(_CONST_FLAG)
    # a registered flag read the ordinary way is fine (negative case)
    d = os.environ.get("LGBM_TPU_CHUNK", "")
    return a, b, c, d
