"""Seeded atomic-write violations: raw writes off the atomic seam."""
import json

from lightgbm_tpu.utils.file_io import open_file, write_atomic


def save_manifest(path, manifest):
    with open(path, "w") as fh:  # SEED atomic-write
        json.dump(manifest, fh)


def save_blob(path, blob):
    fh = open_file(path, mode="wb")  # SEED atomic-write
    fh.write(blob)
    fh.close()


def append_journal(path, line):
    with open(path, "a") as fh:  # SEED atomic-write
        fh.write(line)


def save_ok(path, manifest):
    # negative cases: the blessed seam, and reads
    write_atomic(path, json.dumps(manifest))
    with open(path) as fh:
        return fh.read()
