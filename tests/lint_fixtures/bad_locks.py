"""Seeded lock-discipline violations: unannotated + unguarded shared state."""
import threading


class UnannotatedPump:
    """Shared attr mutated by thread and caller with no guarded-by."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending = []
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while True:
            with self._lock:
                if self._pending:
                    self._pending.pop()  # SEED lock-discipline

    def submit(self, item):
        with self._lock:
            self._pending.append(item)  # (reported at first mutation)


class UnguardedCounter:
    """Annotated, but one caller-side mutation skips the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0          # guarded-by: _lock
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        with self._lock:
            self._count += 1

    def bump(self):
        self._count += 1  # SEED lock-discipline

    def reset(self):
        with self._lock:
            self._count = 0


class DisciplinedQueue:
    """Negative case: annotated, every mutation under the lock (the
    Condition aliases it), helper declares its caller-held lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)
        self._items = []         # guarded-by: _lock
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        with self._ready:
            self._items.pop()
            self._drop_unlocked()

    def _drop_unlocked(self):  # guarded-by-caller: _lock
        self._items.clear()

    def put(self, item):
        with self._lock:
            self._items.append(item)
