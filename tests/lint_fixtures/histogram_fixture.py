"""Seeded parity-hazard fold violations (basename matches 'histogram')."""
import jax.numpy as jnp


def naive_fold(block_hists):
    return jnp.sum(block_hists, axis=0)  # SEED parity-hazard


def blessed_fold(block_hists, init):
    # negative case: inside a carry-in kernel the row-axis fold is the
    # accumulation seam itself
    acc = init + jnp.sum(block_hists, axis=0)
    total = jnp.sum(acc)          # scalar reduction: never flagged
    return acc, total
