"""Seeded docs-sync violations (fixture lives under obs/)."""


def publish(registry, span):
    registry.counter("fixture_metric_never_documented").inc()  # SEED docs-sync
    registry.gauge("fixture_gauge_never_documented").set(1)  # SEED docs-sync
    with span("fixture.span_never_documented"):  # SEED docs-sync
        pass
    # negative case: a documented name passes
    registry.gauge("pod_mfu").set(0.5)
