"""End-to-end training tests on the reference example datasets.

Mirrors the reference test strategy (tests/python_package_test/test_engine.py):
train small models per objective and assert metric thresholds.
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

EXAMPLES = "/root/reference/examples"


def _load(path):
    data = np.loadtxt(path)
    return data[:, 1:], data[:, 0]


@pytest.fixture(scope="module")
def binary_data():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    Xt, yt = _load(f"{EXAMPLES}/binary_classification/binary.test")
    return X, y, Xt, yt


def test_binary(binary_data):
    X, y, Xt, yt = binary_data
    params = {"objective": "binary", "metric": "auc", "verbosity": -1,
              "num_leaves": 31, "learning_rate": 0.1}
    train = lgb.Dataset(X, label=y)
    valid = lgb.Dataset(Xt, label=yt, reference=train)
    evals = {}
    bst = lgb.train(params, train, num_boost_round=50, valid_sets=[valid],
                    evals_result=evals, verbose_eval=False)
    # the reference example reaches ~0.83 AUC on this test split
    auc = evals["valid_0"]["auc"][-1]
    assert auc > 0.80
    pred = bst.predict(Xt)
    assert pred.min() >= 0 and pred.max() <= 1
    from sklearn.metrics import roc_auc_score
    np.testing.assert_allclose(roc_auc_score(yt, pred), auc, atol=1e-6)


def test_regression():
    X, y = _load(f"{EXAMPLES}/regression/regression.train")
    Xt, yt = _load(f"{EXAMPLES}/regression/regression.test")
    params = {"objective": "regression", "metric": "l2", "verbosity": -1}
    evals = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=50,
                    valid_sets=[lgb.Dataset(Xt, label=yt, reference=train)],
                    evals_result=evals, verbose_eval=False)
    l2_start = evals["valid_0"]["l2"][0]
    l2_end = evals["valid_0"]["l2"][-1]
    assert l2_end < l2_start
    assert l2_end < 0.2


def test_regression_l1():
    X, y = _load(f"{EXAMPLES}/regression/regression.train")
    params = {"objective": "regression_l1", "metric": "l1", "verbosity": -1}
    evals = {}
    train = lgb.Dataset(X, label=y)
    lgb.train(params, train, num_boost_round=30,
              valid_sets=[lgb.Dataset(X, label=y, reference=train)],
              evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["l1"][-1] < evals["valid_0"]["l1"][0]


def test_multiclass():
    X, y = _load(f"{EXAMPLES}/multiclass_classification/multiclass.train")
    params = {"objective": "multiclass", "num_class": 5,
              "metric": "multi_logloss", "verbosity": -1}
    evals = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=30,
                    valid_sets=[lgb.Dataset(X, label=y, reference=train)],
                    evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["multi_logloss"][-1] < 1.0
    pred = bst.predict(X)
    assert pred.shape == (len(y), 5)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-5)
    acc = (pred.argmax(axis=1) == y).mean()
    assert acc > 0.6


def test_lambdarank():
    from lightgbm_tpu.io_utils import _load_libsvm
    X, y = _load_libsvm(f"{EXAMPLES}/lambdarank/rank.train")
    group = np.loadtxt(f"{EXAMPLES}/lambdarank/rank.train.query")
    params = {"objective": "lambdarank", "metric": "ndcg", "verbosity": -1,
              "eval_at": [1, 3, 5]}
    evals = {}
    train = lgb.Dataset(X, label=y, group=group)
    lgb.train(params, train, num_boost_round=30,
              valid_sets=[lgb.Dataset(X, label=y, group=group, reference=train)],
              evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["ndcg@3"][-1] > 0.6


def test_early_stopping():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    Xt, yt = _load(f"{EXAMPLES}/binary_classification/binary.test")
    params = {"objective": "binary", "metric": "binary_logloss", "verbosity": -1}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=500,
                    valid_sets=[lgb.Dataset(Xt, label=yt, reference=train)],
                    early_stopping_rounds=5, verbose_eval=False)
    assert bst.best_iteration < 500
    assert bst.current_iteration() <= bst.best_iteration + 5 + 1


def test_missing_values_nan():
    rng = np.random.RandomState(0)
    n = 1000
    X = rng.randn(n, 3)
    y = (X[:, 0] > 0).astype(float)
    X[rng.rand(n) < 0.3, 0] = np.nan  # 30% missing in the signal feature
    params = {"objective": "binary", "metric": "auc", "verbosity": -1,
              "num_leaves": 7}
    evals = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=20,
                    valid_sets=[lgb.Dataset(X, label=y, reference=train)],
                    evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["auc"][-1] > 0.9
    # NaN rows must predict without error
    p = bst.predict(X)
    assert np.isfinite(p).all()


def test_categorical_feature():
    rng = np.random.RandomState(1)
    n = 2000
    cat = rng.randint(0, 10, n).astype(np.float64)
    other = rng.randn(n)
    y = (np.isin(cat, [2, 5, 7]).astype(float) + 0.1 * rng.randn(n) > 0.5)
    X = np.column_stack([cat, other])
    params = {"objective": "binary", "metric": "auc", "verbosity": -1,
              "num_leaves": 7, "min_data_in_leaf": 5}
    evals = {}
    train = lgb.Dataset(X, label=y.astype(float), categorical_feature=[0])
    bst = lgb.train(params, train, num_boost_round=20,
                    valid_sets=[lgb.Dataset(X, label=y.astype(float),
                                            reference=train)],
                    evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["auc"][-1] > 0.95
    assert np.isfinite(bst.predict(X)).all()


def test_goss():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    params = {"objective": "binary", "boosting": "goss", "metric": "auc",
              "verbosity": -1, "learning_rate": 0.1}
    evals = {}
    train = lgb.Dataset(X, label=y)
    lgb.train(params, train, num_boost_round=30,
              valid_sets=[lgb.Dataset(X, label=y, reference=train)],
              evals_result=evals, verbose_eval=False)
    # measured 0.8679; sklearn HistGBM plateau on this data is ~0.883
    assert evals["valid_0"]["auc"][-1] > 0.86


def test_bagging():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    params = {"objective": "binary", "metric": "auc", "verbosity": -1,
              "bagging_fraction": 0.7, "bagging_freq": 1, "bagging_seed": 7}
    evals = {}
    train = lgb.Dataset(X, label=y)
    lgb.train(params, train, num_boost_round=30,
              valid_sets=[lgb.Dataset(X, label=y, reference=train)],
              evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["auc"][-1] > 0.87   # measured 0.8817


def test_model_save_load_roundtrip(tmp_path, binary_data):
    X, y, Xt, yt = binary_data
    params = {"objective": "binary", "verbosity": -1, "num_leaves": 15}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10,
                    verbose_eval=False)
    pred = bst.predict(Xt)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    pred2 = bst2.predict(Xt)
    np.testing.assert_allclose(pred, pred2, rtol=1e-9, atol=1e-12)


def test_continue_train(binary_data):
    X, y, Xt, yt = binary_data
    params = {"objective": "binary", "metric": "auc", "verbosity": -1}
    b1 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10,
                   verbose_eval=False)
    auc1 = _auc(yt, b1.predict(Xt))
    train2 = lgb.Dataset(X, label=y, free_raw_data=False)
    b2 = lgb.train(params, train2, num_boost_round=10, init_model=b1,
                   verbose_eval=False)
    auc2 = _auc(yt, b2.predict(Xt))
    assert b2.num_trees() == 20
    assert auc2 >= auc1 - 0.005


def test_custom_objective(binary_data):
    X, y, Xt, yt = binary_data

    def logloss_obj(score, dataset):
        lbl = dataset.get_label()
        p = 1.0 / (1.0 + np.exp(-score))
        return p - lbl, p * (1 - p)

    params = {"objective": "none", "verbosity": -1}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=30, fobj=logloss_obj,
                    verbose_eval=False)
    auc = _auc(yt, bst.predict(Xt, raw_score=True))
    # test-split ceiling on this dataset is ~0.83 (see test_binary)
    assert auc > 0.80


def test_weights():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    w = np.loadtxt(f"{EXAMPLES}/binary_classification/binary.train.weight")
    params = {"objective": "binary", "metric": "auc", "verbosity": -1}
    evals = {}
    train = lgb.Dataset(X, label=y, weight=w)
    lgb.train(params, train, num_boost_round=20,
              valid_sets=[lgb.Dataset(X, label=y, weight=w, reference=train)],
              evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["auc"][-1] > 0.85   # measured 0.8574


def test_cv():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    params = {"objective": "binary", "metric": "binary_logloss", "verbosity": -1}
    res = lgb.cv(params, lgb.Dataset(X, label=y), num_boost_round=10, nfold=3,
                 stratified=True, shuffle=True)
    assert len(res["binary_logloss-mean"]) == 10
    assert res["binary_logloss-mean"][-1] < res["binary_logloss-mean"][0]


def test_feature_importance(binary_data):
    X, y, _, _ = binary_data
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=10,
                    verbose_eval=False)
    imp = bst.feature_importance("split")
    assert imp.sum() > 0
    gain = bst.feature_importance("gain")
    assert (gain >= 0).all() and gain.sum() > 0


def test_dataset_save_binary(tmp_path):
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    path = str(tmp_path / "data.bin")
    ds.save_binary(path)
    ds2 = lgb.Dataset.load_binary(path)
    np.testing.assert_array_equal(ds.binned, ds2.binned)
    np.testing.assert_array_equal(ds.get_label(), ds2.get_label())
    # trainable from the reloaded dataset
    bst = lgb.train({"objective": "binary", "verbosity": -1}, ds2,
                    num_boost_round=5, verbose_eval=False)
    assert bst.num_trees() == 5


def _auc(y, p):
    from sklearn.metrics import roc_auc_score
    return roc_auc_score(y, p)


def test_dart():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    params = {"objective": "binary", "boosting": "dart", "metric": "auc",
              "verbosity": -1, "drop_rate": 0.5, "skip_drop": 0.0}
    evals = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=25,
                    valid_sets=[lgb.Dataset(X, label=y, reference=train)],
                    evals_result=evals, verbose_eval=False)
    traj = evals["valid_0"]["auc"]
    # drop_rate=0.5 + skip_drop=0 is aggressive dropout; measured 0.798
    assert traj[-1] > 0.78
    p = bst.predict(X)
    assert np.isfinite(p).all() and 0 <= p.min() and p.max() <= 1


def test_random_forest():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    params = {"objective": "binary", "boosting": "rf", "metric": "auc",
              "verbosity": -1, "bagging_freq": 1, "bagging_fraction": 0.6,
              "feature_fraction": 0.8}
    evals = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=20,
                    valid_sets=[lgb.Dataset(X, label=y, reference=train)],
                    evals_result=evals, verbose_eval=False)
    # measured 0.8165; sklearn RandomForest at matched capacity gets 0.8121
    assert evals["valid_0"]["auc"][-1] > 0.80
    p = bst.predict(X)
    # averaged probabilities, not a boosted sum
    assert np.isfinite(p).all() and 0 <= p.min() and p.max() <= 1
    # rf without bagging must be rejected (reference CHECK, rf.hpp:28)
    with pytest.raises(ValueError):
        lgb.train({"objective": "binary", "boosting": "rf", "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=2)


def test_dart_rf_model_roundtrip(tmp_path):
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    for boosting, extra in (("dart", {"drop_rate": 0.3, "skip_drop": 0.2}),
                            ("rf", {"bagging_freq": 1, "bagging_fraction": 0.7})):
        params = {"objective": "binary", "verbosity": -1, "boosting": boosting,
                  "num_leaves": 7, **extra}
        bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6,
                        verbose_eval=False)
        pred = bst.predict(X)
        path = str(tmp_path / f"{boosting}.txt")
        bst.save_model(path)
        pred2 = lgb.Booster(model_file=path).predict(X)
        np.testing.assert_allclose(pred, pred2, rtol=1e-6, atol=1e-9)
