"""End-to-end training tests on the reference example datasets.

Mirrors the reference test strategy (tests/python_package_test/test_engine.py):
train small models per objective and assert metric thresholds.
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

EXAMPLES = "/root/reference/examples"


def _load(path):
    data = np.loadtxt(path)
    return data[:, 1:], data[:, 0]


@pytest.fixture(scope="module")
def binary_data():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    Xt, yt = _load(f"{EXAMPLES}/binary_classification/binary.test")
    return X, y, Xt, yt


def test_binary(binary_data):
    X, y, Xt, yt = binary_data
    params = {"objective": "binary", "metric": "auc", "verbosity": -1,
              "num_leaves": 31, "learning_rate": 0.1}
    train = lgb.Dataset(X, label=y)
    valid = lgb.Dataset(Xt, label=yt, reference=train)
    evals = {}
    bst = lgb.train(params, train, num_boost_round=50, valid_sets=[valid],
                    evals_result=evals, verbose_eval=False)
    # the reference example reaches ~0.83 AUC on this test split
    auc = evals["valid_0"]["auc"][-1]
    assert auc > 0.80
    pred = bst.predict(Xt)
    assert pred.min() >= 0 and pred.max() <= 1
    from sklearn.metrics import roc_auc_score
    np.testing.assert_allclose(roc_auc_score(yt, pred), auc, atol=1e-6)


def test_regression():
    X, y = _load(f"{EXAMPLES}/regression/regression.train")
    Xt, yt = _load(f"{EXAMPLES}/regression/regression.test")
    params = {"objective": "regression", "metric": "l2", "verbosity": -1}
    evals = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=50,
                    valid_sets=[lgb.Dataset(Xt, label=yt, reference=train)],
                    evals_result=evals, verbose_eval=False)
    l2_start = evals["valid_0"]["l2"][0]
    l2_end = evals["valid_0"]["l2"][-1]
    assert l2_end < l2_start
    assert l2_end < 0.2


def test_regression_l1():
    X, y = _load(f"{EXAMPLES}/regression/regression.train")
    params = {"objective": "regression_l1", "metric": "l1", "verbosity": -1}
    evals = {}
    train = lgb.Dataset(X, label=y)
    lgb.train(params, train, num_boost_round=30,
              valid_sets=[lgb.Dataset(X, label=y, reference=train)],
              evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["l1"][-1] < evals["valid_0"]["l1"][0]


def test_multiclass():
    X, y = _load(f"{EXAMPLES}/multiclass_classification/multiclass.train")
    params = {"objective": "multiclass", "num_class": 5,
              "metric": "multi_logloss", "verbosity": -1}
    evals = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=30,
                    valid_sets=[lgb.Dataset(X, label=y, reference=train)],
                    evals_result=evals, verbose_eval=False)
    # measured 1.1104 @30 rounds; reference at identical config: 1.1089
    # (with the reference's flat-2.0 softmax hessian; see test_parity.py)
    assert evals["valid_0"]["multi_logloss"][-1] < 1.15
    pred = bst.predict(X)
    assert pred.shape == (len(y), 5)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-5)
    acc = (pred.argmax(axis=1) == y).mean()
    assert acc > 0.6


def test_lambdarank():
    from lightgbm_tpu.io_utils import _load_libsvm
    X, y = _load_libsvm(f"{EXAMPLES}/lambdarank/rank.train")
    group = np.loadtxt(f"{EXAMPLES}/lambdarank/rank.train.query")
    params = {"objective": "lambdarank", "metric": "ndcg", "verbosity": -1,
              "eval_at": [1, 3, 5]}
    evals = {}
    train = lgb.Dataset(X, label=y, group=group)
    lgb.train(params, train, num_boost_round=30,
              valid_sets=[lgb.Dataset(X, label=y, group=group, reference=train)],
              evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["ndcg@3"][-1] > 0.6


def test_early_stopping():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    Xt, yt = _load(f"{EXAMPLES}/binary_classification/binary.test")
    params = {"objective": "binary", "metric": "binary_logloss", "verbosity": -1}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=500,
                    valid_sets=[lgb.Dataset(Xt, label=yt, reference=train)],
                    early_stopping_rounds=5, verbose_eval=False)
    assert bst.best_iteration < 500
    assert bst.current_iteration() <= bst.best_iteration + 5 + 1


def test_missing_values_nan():
    rng = np.random.RandomState(0)
    n = 1000
    X = rng.randn(n, 3)
    y = (X[:, 0] > 0).astype(float)
    X[rng.rand(n) < 0.3, 0] = np.nan  # 30% missing in the signal feature
    params = {"objective": "binary", "metric": "auc", "verbosity": -1,
              "num_leaves": 7}
    evals = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=20,
                    valid_sets=[lgb.Dataset(X, label=y, reference=train)],
                    evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["auc"][-1] > 0.9
    # NaN rows must predict without error
    p = bst.predict(X)
    assert np.isfinite(p).all()


def test_categorical_feature():
    rng = np.random.RandomState(1)
    n = 2000
    cat = rng.randint(0, 10, n).astype(np.float64)
    other = rng.randn(n)
    y = (np.isin(cat, [2, 5, 7]).astype(float) + 0.1 * rng.randn(n) > 0.5)
    X = np.column_stack([cat, other])
    params = {"objective": "binary", "metric": "auc", "verbosity": -1,
              "num_leaves": 7, "min_data_in_leaf": 5}
    evals = {}
    train = lgb.Dataset(X, label=y.astype(float), categorical_feature=[0])
    bst = lgb.train(params, train, num_boost_round=20,
                    valid_sets=[lgb.Dataset(X, label=y.astype(float),
                                            reference=train)],
                    evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["auc"][-1] > 0.95
    assert np.isfinite(bst.predict(X)).all()


def test_goss():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    params = {"objective": "binary", "boosting": "goss", "metric": "auc",
              "verbosity": -1, "learning_rate": 0.1}
    evals = {}
    train = lgb.Dataset(X, label=y)
    lgb.train(params, train, num_boost_round=30,
              valid_sets=[lgb.Dataset(X, label=y, reference=train)],
              evals_result=evals, verbose_eval=False)
    # measured 0.8679.  Parity note (see tests/test_parity.py docstring): in
    # this reference checkout GOSS never actually samples (gbdt.cpp:214 guard
    # vs goss.hpp:129), so reference "goss" == plain gbdt == 0.8826 here;
    # this repo implements the intended sampling, which costs ~0.015 train
    # AUC at 30 rounds on this small dataset by design.
    assert evals["valid_0"]["auc"][-1] > 0.86


def test_bagging():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    params = {"objective": "binary", "metric": "auc", "verbosity": -1,
              "bagging_fraction": 0.7, "bagging_freq": 1, "bagging_seed": 7}
    evals = {}
    train = lgb.Dataset(X, label=y)
    lgb.train(params, train, num_boost_round=30,
              valid_sets=[lgb.Dataset(X, label=y, reference=train)],
              evals_result=evals, verbose_eval=False)
    # measured 0.8817; reference at identical config measures 0.8821
    # (parity verified in tests/test_parity.py)
    assert evals["valid_0"]["auc"][-1] > 0.87


def test_model_save_load_roundtrip(tmp_path, binary_data):
    X, y, Xt, yt = binary_data
    params = {"objective": "binary", "verbosity": -1, "num_leaves": 15}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10,
                    verbose_eval=False)
    pred = bst.predict(Xt)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    pred2 = bst2.predict(Xt)
    np.testing.assert_allclose(pred, pred2, rtol=1e-9, atol=1e-12)


def test_continue_train(binary_data):
    X, y, Xt, yt = binary_data
    params = {"objective": "binary", "metric": "auc", "verbosity": -1}
    b1 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10,
                   verbose_eval=False)
    auc1 = _auc(yt, b1.predict(Xt))
    train2 = lgb.Dataset(X, label=y, free_raw_data=False)
    b2 = lgb.train(params, train2, num_boost_round=10, init_model=b1,
                   verbose_eval=False)
    auc2 = _auc(yt, b2.predict(Xt))
    assert b2.num_trees() == 20
    assert auc2 >= auc1 - 0.005


def test_custom_objective(binary_data):
    X, y, Xt, yt = binary_data

    def logloss_obj(score, dataset):
        lbl = dataset.get_label()
        p = 1.0 / (1.0 + np.exp(-score))
        return p - lbl, p * (1 - p)

    params = {"objective": "none", "verbosity": -1}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=30, fobj=logloss_obj,
                    verbose_eval=False)
    auc = _auc(yt, bst.predict(Xt, raw_score=True))
    # test-split ceiling on this dataset is ~0.83 (see test_binary)
    assert auc > 0.80


def test_weights():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    w = np.loadtxt(f"{EXAMPLES}/binary_classification/binary.train.weight")
    params = {"objective": "binary", "metric": "auc", "verbosity": -1}
    evals = {}
    train = lgb.Dataset(X, label=y, weight=w)
    lgb.train(params, train, num_boost_round=20,
              valid_sets=[lgb.Dataset(X, label=y, weight=w, reference=train)],
              evals_result=evals, verbose_eval=False)
    # measured 0.8574; reference at identical config measures 0.8575
    assert evals["valid_0"]["auc"][-1] > 0.85


def test_cv():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    params = {"objective": "binary", "metric": "binary_logloss", "verbosity": -1}
    res = lgb.cv(params, lgb.Dataset(X, label=y), num_boost_round=10, nfold=3,
                 stratified=True, shuffle=True)
    assert len(res["binary_logloss-mean"]) == 10
    assert res["binary_logloss-mean"][-1] < res["binary_logloss-mean"][0]


def test_feature_importance(binary_data):
    X, y, _, _ = binary_data
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=10,
                    verbose_eval=False)
    imp = bst.feature_importance("split")
    assert imp.sum() > 0
    gain = bst.feature_importance("gain")
    assert (gain >= 0).all() and gain.sum() > 0


def test_dataset_save_binary(tmp_path):
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    path = str(tmp_path / "data.bin")
    ds.save_binary(path)
    ds2 = lgb.Dataset.load_binary(path)
    np.testing.assert_array_equal(ds.binned, ds2.binned)
    np.testing.assert_array_equal(ds.get_label(), ds2.get_label())
    # trainable from the reloaded dataset
    bst = lgb.train({"objective": "binary", "verbosity": -1}, ds2,
                    num_boost_round=5, verbose_eval=False)
    assert bst.num_trees() == 5


def _auc(y, p):
    from sklearn.metrics import roc_auc_score
    return roc_auc_score(y, p)


def test_dart():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    params = {"objective": "binary", "boosting": "dart", "metric": "auc",
              "verbosity": -1, "drop_rate": 0.5, "skip_drop": 0.0}
    evals = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=25,
                    valid_sets=[lgb.Dataset(X, label=y, reference=train)],
                    evals_result=evals, verbose_eval=False)
    traj = evals["valid_0"]["auc"]
    # drop_rate=0.5 + skip_drop=0 is aggressive dropout; measured 0.798
    assert traj[-1] > 0.78
    p = bst.predict(X)
    assert np.isfinite(p).all() and 0 <= p.min() and p.max() <= 1


def test_random_forest():
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    params = {"objective": "binary", "boosting": "rf", "metric": "auc",
              "verbosity": -1, "bagging_freq": 1, "bagging_fraction": 0.6,
              "feature_fraction": 0.8}
    evals = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=20,
                    valid_sets=[lgb.Dataset(X, label=y, reference=train)],
                    evals_result=evals, verbose_eval=False)
    # measured 0.8165; sklearn RandomForest at matched capacity gets 0.8121
    assert evals["valid_0"]["auc"][-1] > 0.80
    p = bst.predict(X)
    # averaged probabilities, not a boosted sum
    assert np.isfinite(p).all() and 0 <= p.min() and p.max() <= 1
    # rf without bagging must be rejected (reference CHECK, rf.hpp:28)
    with pytest.raises(ValueError):
        lgb.train({"objective": "binary", "boosting": "rf", "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=2)


def test_dart_rf_model_roundtrip(tmp_path):
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    for boosting, extra in (("dart", {"drop_rate": 0.3, "skip_drop": 0.2}),
                            ("rf", {"bagging_freq": 1, "bagging_fraction": 0.7})):
        params = {"objective": "binary", "verbosity": -1, "boosting": boosting,
                  "num_leaves": 7, **extra}
        bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6,
                        verbose_eval=False)
        pred = bst.predict(X)
        path = str(tmp_path / f"{boosting}.txt")
        bst.save_model(path)
        pred2 = lgb.Booster(model_file=path).predict(X)
        np.testing.assert_allclose(pred, pred2, rtol=1e-6, atol=1e-9)


def test_monotone_constraints():
    # reference: test_engine.py:1000 test_monotone_constraint — but stricter:
    # we assert actual prediction monotonicity (needs descendant bound
    # propagation, monotone_constraints.hpp:44, not just the local check)
    rng = np.random.RandomState(42)
    n = 2000
    x0, x1, x2 = rng.rand(n), rng.rand(n), rng.rand(n)
    y = (5 * x0 + np.sin(10 * np.pi * x0)
         - 5 * x1 - np.cos(10 * np.pi * x1)
         + 10 * x2 + rng.rand(n))
    X = np.column_stack([x0, x1, x2])
    params = {"objective": "regression", "metric": "l2", "verbosity": -1,
              "monotone_constraints": [1, -1, 0], "num_leaves": 31,
              "min_data_in_leaf": 5}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=30)

    grid = np.linspace(0.0, 1.0, 101)
    base = rng.rand(10, 3)
    for row in base:
        sweep = np.tile(row, (grid.size, 1))
        sweep[:, 0] = grid
        p = bst.predict(sweep)
        assert (np.diff(p) >= -1e-10).all(), "feature 0 must be non-decreasing"
        sweep = np.tile(row, (grid.size, 1))
        sweep[:, 1] = grid
        p = bst.predict(sweep)
        assert (np.diff(p) <= 1e-10).all(), "feature 1 must be non-increasing"


def test_dart_boost_from_average_applied_once():
    # regression with a large label mean: a double-added init score (the
    # round-1 DART bug) shifts every gradient by ~mean and wrecks the fit
    rng = np.random.RandomState(0)
    X = rng.rand(600, 5)
    y = 100.0 + X @ np.arange(1.0, 6.0) + rng.randn(600) * 0.1
    params = {"objective": "regression", "boosting": "dart", "metric": "l2",
              "verbosity": -1, "num_leaves": 15, "min_data_in_leaf": 5,
              "drop_rate": 0.2, "learning_rate": 0.2}
    evals = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(params, train, num_boost_round=30,
                    valid_sets=[lgb.Dataset(X, label=y, reference=train)],
                    evals_result=evals, verbose_eval=False)
    pred = bst.predict(X)
    # eval metric must agree with saved-model predictions: with the init
    # score double-added, internal scores sit ~100 above what the saved
    # model predicts and the two RMSEs diverge wildly.  (Mean drift of a
    # few units is genuine DART: dropped early trees carry the folded-in
    # init bias and are renormalized — the reference behaves the same.)
    rmse_pred = float(np.sqrt(np.mean((pred - y) ** 2)))
    rmse_eval = float(np.sqrt(evals["valid_0"]["l2"][-1]))
    assert abs(rmse_pred - rmse_eval) < 0.05 * max(rmse_eval, 1e-3)
    # and the fit must actually converge toward the target, not to a
    # double-shifted score (which plateaus ~100 away)
    assert rmse_pred < 8.0


def test_dart_continue_training_drops_only_new_trees(tmp_path):
    # reference: dart.hpp:108 drops num_init_iteration_ + i — init-model
    # trees are never dropped/rescaled during continued DART training
    X, y = _load(f"{EXAMPLES}/binary_classification/binary.train")
    params = {"objective": "binary", "verbosity": -1, "num_leaves": 15}
    base = lgb.train(params, lgb.Dataset(X, label=y, free_raw_data=False),
                     num_boost_round=5)
    init_leaf_values = [m.leaf_value.copy() for m in base.boosting.models]

    dart_params = dict(params, boosting="dart", drop_rate=1.0, skip_drop=0.0)
    bst = lgb.train(dart_params,
                    lgb.Dataset(X, label=y, free_raw_data=False),
                    num_boost_round=5, init_model=base)
    assert bst.boosting.num_init_iteration == 5
    assert len(bst.boosting.models) == 10
    # init trees untouched (drop_rate=1 rescales every this-run tree)
    for m, lv in zip(bst.boosting.models[:5], init_leaf_values):
        np.testing.assert_array_equal(m.leaf_value, lv)
    p = bst.predict(X)
    assert np.isfinite(p).all()


def test_extra_trees(binary_data):
    # reference: test_engine.py:1961 — extra_trees must change the trained
    # model (it was a parsed-but-ignored parameter in round 1) and still learn
    X, y, Xt, yt = binary_data
    base = {"objective": "binary", "metric": "auc", "verbosity": -1,
            "num_leaves": 15}
    ev_n, ev_x, ev_x2 = {}, {}, {}

    def run(extra, seed, ev):
        params = dict(base, extra_trees=extra, extra_trees_seed=seed)
        train = lgb.Dataset(X, label=y)
        return lgb.train(params, train, num_boost_round=10,
                         valid_sets=[lgb.Dataset(Xt, label=yt, reference=train)],
                         evals_result=ev, verbose_eval=False)

    bst_n = run(False, 6, ev_n)
    bst_x = run(True, 6, ev_x)
    bst_x2 = run(True, 6, ev_x2)
    # deterministic under a fixed seed
    for m1, m2 in zip(bst_x.boosting.models, bst_x2.boosting.models):
        np.testing.assert_array_equal(m1.threshold_in_bin, m2.threshold_in_bin)
    # random thresholds actually used: models differ from exact search
    same = all(
        np.array_equal(mn.threshold_in_bin, mx.threshold_in_bin)
        and np.array_equal(mn.split_feature, mx.split_feature)
        for mn, mx in zip(bst_n.boosting.models, bst_x.boosting.models))
    assert not same, "extra_trees must alter threshold selection"
    # and still learn (measured: 0.779 at 10 rounds; exact search 0.787)
    assert ev_x["valid_0"]["auc"][-1] > 0.74


def test_feature_fraction_bynode(binary_data):
    X, y, Xt, yt = binary_data
    base = {"objective": "binary", "metric": "auc", "verbosity": -1,
            "num_leaves": 31, "feature_fraction_seed": 3}
    ev = {}

    def run(frac, ev_):
        params = dict(base, feature_fraction_bynode=frac)
        train = lgb.Dataset(X, label=y)
        return lgb.train(params, train, num_boost_round=10,
                         valid_sets=[lgb.Dataset(Xt, label=yt, reference=train)],
                         evals_result=ev_, verbose_eval=False)

    bst_full = run(1.0, {})
    bst_bn = run(0.25, ev)
    # per-node sampling must change which features are split on
    feats_full = [m.split_feature.copy() for m in bst_full.boosting.models]
    feats_bn = [m.split_feature.copy() for m in bst_bn.boosting.models]
    assert any(not np.array_equal(a, b) for a, b in zip(feats_full, feats_bn))
    # a single node sees only ~7 of 28 features, but across nodes coverage
    # stays broad and the model still learns (measured: 0.798 at 10 rounds)
    assert ev["valid_0"]["auc"][-1] > 0.76


def test_refit(binary_data, tmp_path):
    # reference: test_engine.py:1083 test_refit + GBDT::RefitTree
    X, y, Xt, yt = binary_data
    params = {"objective": "binary", "metric": "binary_logloss",
              "verbosity": -1, "num_leaves": 15, "min_data_in_leaf": 20}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10)
    err_orig = float(np.mean((bst.predict(Xt) > 0.5) != yt))

    # decay 0: leaf values entirely re-fit to the new (test) data
    refitted = bst.refit(Xt, yt, decay_rate=0.0)
    err_refit = float(np.mean((refitted.predict(Xt) > 0.5) != yt))
    assert err_refit < err_orig  # reference asserts the same inequality
    # structures untouched, only leaf values changed
    for m0, m1 in zip(bst.models, refitted.models):
        np.testing.assert_array_equal(m0.split_feature, m1.split_feature)
        np.testing.assert_array_equal(m0.threshold_in_bin, m1.threshold_in_bin)
        assert not np.allclose(m0.leaf_value, m1.leaf_value)
    # decay 1: leaf values unchanged
    kept = bst.refit(Xt, yt, decay_rate=1.0)
    for m0, m1 in zip(bst.models, kept.models):
        np.testing.assert_allclose(m0.leaf_value, m1.leaf_value, rtol=1e-12)

    # refit from a loaded model file (no training state)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    loaded = lgb.Booster(model_file=path)
    refit2 = loaded.refit(Xt, yt, decay_rate=0.0)
    np.testing.assert_allclose(refit2.predict(Xt), refitted.predict(Xt),
                               rtol=1e-5, atol=1e-7)


def test_early_stopped_model_round_trips_at_best_iteration(binary_data):
    """reference: Booster.save_model defaults num_iteration=best_iteration
    (basic.py:2407) — a save/load round trip must not change predictions."""
    X, y, Xt, yt = binary_data
    tr = lgb.Dataset(X, label=y)
    va = tr.create_valid(Xt, label=yt)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "metric": "auc", "verbosity": -1},
                    tr, num_boost_round=50, valid_sets=[va],
                    callbacks=[lgb.early_stopping(3, verbose=False)])
    assert 0 < bst.best_iteration < 50
    pred = bst.predict(X)
    re = lgb.Booster(model_str=bst.model_to_string())
    assert re.num_trees() == bst.best_iteration
    np.testing.assert_allclose(re.predict(X), pred, rtol=1e-9)
    # explicit num_iteration=0 still saves everything
    full = lgb.Booster(model_str=bst.model_to_string(num_iteration=0))
    assert full.num_trees() == bst.num_trees()


def test_compile_cache_env_wiring(tmp_path, monkeypatch):
    """LGBM_TPU_COMPILE_CACHE=<dir> wires the persistent XLA compile
    cache at engine init: the dir gets created and populated, and a
    second (warm) training of the same shape reuses it byte-for-byte."""
    import jax

    from lightgbm_tpu.utils.platform import (compile_cache_entries,
                                             enable_compile_cache)
    rng = np.random.RandomState(0)
    X = rng.randn(400, 6)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    cache = tmp_path / "xla_cache"
    monkeypatch.setenv("LGBM_TPU_COMPILE_CACHE", str(cache))
    prev = jax.config.jax_compilation_cache_dir
    try:
        params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
        m1 = lgb.train(params, lgb.Dataset(X, label=y), 3,
                       verbose_eval=False).model_to_string()
        assert jax.config.jax_compilation_cache_dir == str(cache)
        assert cache.is_dir()
        n_cold = compile_cache_entries(str(cache))
        m2 = lgb.train(params, lgb.Dataset(X, label=y), 3,
                       verbose_eval=False).model_to_string()
        assert m1 == m2
        assert compile_cache_entries(str(cache)) >= n_cold
        # disabled spellings are no-ops
        monkeypatch.setenv("LGBM_TPU_COMPILE_CACHE", "off")
        assert enable_compile_cache() is None
        monkeypatch.delenv("LGBM_TPU_COMPILE_CACHE")
        assert enable_compile_cache() is None
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
