"""Python API parity: the reference package's Dataset/Booster method
surface (python-package/lightgbm/basic.py) on the TPU implementation."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture
def trained():
    rng = np.random.RandomState(3)
    n = 2000
    X = rng.rand(n, 8).astype(np.float32)
    y = ((X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.randn(n)) > 0.7).astype(np.float32)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    dv = ds.create_valid(X[:500], label=y[:500])
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "metric": "binary_logloss"}
    bst = lgb.train(params, ds, num_boost_round=6, valid_sets=[dv],
                    valid_names=["v0"])
    return X, y, ds, dv, bst


def test_dataset_fields_and_params():
    rng = np.random.RandomState(0)
    X = rng.rand(100, 4).astype(np.float32)
    ds = lgb.Dataset(X, params={"max_bin": 16})
    ds.set_field("label", np.arange(100) % 2)
    ds.set_field("weight", np.ones(100))
    ds.set_field("init_score", np.zeros(100))
    ds.set_field("group", [60, 40])
    np.testing.assert_array_equal(ds.get_field("label"), np.arange(100) % 2)
    np.testing.assert_array_equal(ds.get_field("group"), [0, 60, 100])
    np.testing.assert_array_equal(ds.get_group(), [60, 40])
    assert ds.get_params() == {"max_bin": 16}
    with pytest.raises(ValueError):
        ds.set_field("nope", [1])
    ds.set_field("weight", None)
    assert ds.get_field("weight") is None


def test_dataset_ref_chain_and_setters():
    rng = np.random.RandomState(0)
    X = rng.rand(50, 3).astype(np.float32)
    a = lgb.Dataset(X, label=np.zeros(50))
    b = lgb.Dataset(X, label=np.zeros(50))
    b.set_reference(a)
    c = lgb.Dataset(X, label=np.zeros(50), reference=b)
    chain = c.get_ref_chain()
    assert chain == {a, b, c}
    a.set_feature_name([f"f{i}" for i in range(3)])
    a.construct()
    assert a.feature_names == ["f0", "f1", "f2"]
    assert a.num_feature() == 3
    with pytest.raises(RuntimeError):
        a.set_reference(b)


def test_dataset_get_data_and_free():
    rng = np.random.RandomState(0)
    X = rng.rand(50, 3).astype(np.float32)
    kept = lgb.Dataset(X, label=np.zeros(50), free_raw_data=False).construct()
    assert kept.get_data() is not None
    freed = lgb.Dataset(X, label=np.zeros(50)).construct()
    with pytest.raises(RuntimeError):
        freed.get_data()


def test_add_features_from_matches_joint_training():
    rng = np.random.RandomState(1)
    n = 1500
    Xa = rng.rand(n, 3).astype(np.float32)
    Xb = rng.rand(n, 2).astype(np.float32)
    y = ((Xa[:, 0] + Xb[:, 1] + 0.1 * rng.randn(n)) > 1.0).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "enable_bundle": False}

    da = lgb.Dataset(Xa, label=y, params=params).construct()
    db = lgb.Dataset(Xb, params=params).construct()
    da.add_features_from(db)
    assert da.num_feature() == 5
    merged = lgb.train(params, da, num_boost_round=5)

    joint = lgb.train(params, lgb.Dataset(np.hstack([Xa, Xb]), label=y,
                                          params=params), num_boost_round=5)
    Xfull = np.hstack([Xa, Xb])
    np.testing.assert_allclose(merged.predict(Xfull), joint.predict(Xfull),
                               rtol=1e-6)


def test_booster_attr_and_train_data_name(trained):
    _, _, ds, dv, bst = trained
    assert bst.attr("missing") is None
    bst.set_attr(alpha="1", beta="x")
    assert bst.attr("alpha") == "1"
    bst.set_attr(alpha=None)
    assert bst.attr("alpha") is None
    with pytest.raises(ValueError):
        bst.set_attr(gamma=3)
    bst.set_train_data_name("mytrain")
    assert bst.eval_train()[0][0] == "mytrain"


def test_booster_eval_on_datasets(trained):
    _, _, ds, dv, bst = trained
    tr = bst.eval(ds, "anything")
    assert tr and tr[0][0] == "anything"   # reference uses the passed name
    ev = bst.eval(dv, "renamed")
    assert ev and ev[0][0] == "renamed"
    assert ev[0][1] == "binary_logloss"
    with pytest.raises(ValueError):
        bst.eval(lgb.Dataset(np.zeros((5, 8)), label=np.zeros(5)), "x")


def test_booster_bounds_and_leaf_output(trained):
    _, _, _, _, bst = trained
    lo, hi = bst.lower_bound(), bst.upper_bound()
    assert lo <= hi
    m0 = bst.models[0]
    assert bst.get_leaf_output(0, 0) == pytest.approx(float(m0.leaf_value[0]))
    total_lo = sum(float(np.min(m.leaf_value[:m.num_leaves]))
                   for m in bst.models)
    assert lo == pytest.approx(total_lo)


def test_booster_model_from_string_and_num_feature(trained):
    X, _, _, _, bst = trained
    s = bst.model_to_string()
    pred = bst.predict(X)
    b2 = lgb.Booster(model_str=s)
    b2.model_from_string(s)
    np.testing.assert_allclose(b2.predict(X), pred, rtol=1e-9)
    assert b2.num_feature() == 8


def test_booster_shuffle_models(trained):
    X, _, _, _, bst = trained
    pred_before = bst.predict(X)
    before = [m for m in bst.models]
    bst.shuffle_models()
    after = [m for m in bst.models]
    assert sorted(map(id, before)) == sorted(map(id, after))
    assert list(map(id, before)) != list(map(id, after))   # must move some
    # prediction = sum over trees, invariant under order
    np.testing.assert_allclose(bst.predict(X), pred_before, rtol=1e-6)


def test_parameters_doc_current():
    """docs/Parameters.rst is GENERATED from the Config dataclass (the
    reference generates its Parameters.rst from config.h comments via
    helpers/parameter_generator.py); drift is a test failure."""
    import os
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "gen_parameters_doc.py"),
         "--check"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr + r.stdout


def test_parameters_doc_lists_every_config_field():
    """Every Config field — including the quantized-training keys — must
    appear in docs/Parameters.rst, and the check mode must FAIL BY NAME
    when one is removed (config surface can't drift undocumented)."""
    import dataclasses
    import os
    import subprocess
    import sys

    from lightgbm_tpu.config import Config
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rst = open(os.path.join(root, "docs", "Parameters.rst")).read()
    for f in dataclasses.fields(Config):
        assert f"``{f.name}``" in rst, f"{f.name} missing from Parameters.rst"
    for key in ("use_quantized_grad", "num_grad_quant_bins",
                "quant_train_renew_leaf", "stochastic_rounding"):
        assert f"``{key}``" in rst

    # simulate drift: drop the use_quantized_grad line from a copy and
    # assert --check --out fails naming the field
    import tempfile
    broken = "\n".join(ln for ln in rst.splitlines()
                       if "``use_quantized_grad``" not in ln) + "\n"
    with tempfile.NamedTemporaryFile("w", suffix=".rst",
                                     delete=False) as fh:
        fh.write(broken)
        path = fh.name
    try:
        r = subprocess.run(
            [sys.executable,
             os.path.join(root, "tools", "gen_parameters_doc.py"),
             "--check", "--out", path], capture_output=True, text=True)
        assert r.returncode == 1
        assert "use_quantized_grad" in r.stderr
    finally:
        os.unlink(path)
