"""CLI application tests.

Mirrors the reference CLI-vs-Python consistency strategy
(tests/c_api_test + tests/python_package_test/test_consistency.py:10-60):
train via the stock examples/*/train.conf through the CLI, predict through
the CLI, and cross-check against the Python API on the same data.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb

EXAMPLES = "/root/reference/examples"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(args, cwd):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, "-m", "lightgbm_tpu"] + args,
                          cwd=cwd, env=env, capture_output=True, text=True,
                          timeout=600)


def test_cli_train_predict_consistency(tmp_path):
    conf = f"{EXAMPLES}/binary_classification/train.conf"
    r = _run_cli([f"config={conf}", "num_trees=15", "metric_freq=10",
                  "output_model=model.txt"], cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr[-2000:]
    assert (tmp_path / "model.txt").exists()

    r2 = _run_cli(["task=predict",
                   f"data={EXAMPLES}/binary_classification/binary.test",
                   "input_model=model.txt",
                   "output_result=preds.txt"], cwd=str(tmp_path))
    assert r2.returncode == 0, r2.stderr[-2000:]
    cli_pred = np.loadtxt(tmp_path / "preds.txt")

    # Python API prediction from the same saved model must agree exactly
    bst = lgb.Booster(model_file=str(tmp_path / "model.txt"))
    data = np.loadtxt(f"{EXAMPLES}/binary_classification/binary.test")
    py_pred = bst.predict(data[:, 1:])
    np.testing.assert_allclose(cli_pred, py_pred, rtol=1e-9, atol=1e-12)


def test_cli_convert_model_compiles_and_matches(tmp_path):
    import shutil
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    data = np.loadtxt(f"{EXAMPLES}/binary_classification/binary.train")
    X, y = data[:200, 1:], data[:200, 0]
    bst = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 7},
                    lgb.Dataset(X, label=y), num_boost_round=4,
                    verbose_eval=False)
    bst.save_model(str(tmp_path / "m.txt"))
    r = _run_cli(["task=convert_model", "input_model=m.txt",
                  "convert_model=m.cpp"], cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr[-2000:]

    harness = r"""
#include <cstdio>
#include <cstdlib>
extern "C" void Predict(const double*, double*);
int main(int argc, char** argv) {
  int nf = atoi(argv[1]);
  double feat[256], out[8];
  while (true) {
    for (int i = 0; i < nf; ++i)
      if (scanf("%lf", &feat[i]) != 1) return 0;
    Predict(feat, out);
    printf("%.17g\n", out[0]);
  }
}
"""
    (tmp_path / "main.cpp").write_text(harness)
    c = subprocess.run(["g++", "-O1", "-o", "pred", "m.cpp", "main.cpp"],
                       cwd=str(tmp_path), capture_output=True, text=True)
    assert c.returncode == 0, c.stderr[-2000:]
    Xt = X[:32]
    inp = "\n".join(" ".join(f"{v:.17g}" for v in row) for row in Xt)
    run = subprocess.run(["./pred", str(X.shape[1])], input=inp,
                         cwd=str(tmp_path), capture_output=True, text=True)
    cpp_raw = np.array([float(v) for v in run.stdout.split()])
    py_raw = bst.predict(Xt, raw_score=True)
    np.testing.assert_allclose(cpp_raw, py_raw, rtol=1e-12, atol=1e-12)
