"""Timer/tracing subsystem (reference: Common::Timer + FunctionTimer,
include/LightGBM/utils/common.h:1026-1110, -DUSE_TIMETAG)."""
import io

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.timer import Timer, function_timer, global_timer


def test_timer_accumulates_and_prints():
    t = Timer(enabled=True)
    with t.section("a"):
        pass
    with t.section("a"):
        pass
    with t.section("b"):
        pass
    items = t.items()
    assert items["a"][0] == 2 and items["b"][0] == 1
    buf = io.StringIO()
    t.print(file=buf)
    out = buf.getvalue()
    assert "a" in out and "b" in out and "calls" in out

    @function_timer("fn", timer=t)
    def f(x):
        return x + 1

    assert f(1) == 2
    assert t.items()["fn"][0] == 1


def test_timer_disabled_is_noop():
    t = Timer(enabled=False)
    with t.section("x"):
        pass
    assert t.items() == {}


def test_training_tags_hot_paths(monkeypatch):
    """The tagged sections mirror the reference's global_timer tags
    (gbdt.cpp:153,211; serial_tree_learner.cpp:150).  Pinned to the
    legacy per-iteration path (LGBM_TPU_CHUNK=0); fused macro-steps
    amortize rounds over chunks and are checked separately below."""
    monkeypatch.setenv("LGBM_TPU_CHUNK", "0")
    global_timer.reset()
    global_timer.enable()
    try:
        rng = np.random.RandomState(0)
        X = rng.rand(500, 4)
        y = (X[:, 0] > 0.5).astype(np.float32)
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=3)
        bst.predict(X[:10])
        items = global_timer.items()
        for key in ("Dataset::Construct", "GBDT::TrainOneIter",
                    "TreeLearner::Train(dispatch)",
                    "GBDT::FinishIter(host trees)", "Booster::Predict"):
            assert key in items, (key, sorted(items))
        assert items["GBDT::TrainOneIter"][0] == 3
    finally:
        global_timer.disable()
        global_timer.reset()


def test_training_tags_chunked():
    """The fused macro-step path keeps the dispatch/finish tags: 3 rounds
    under the default chunk gate = one c=2 chunk + one c=1 step, each
    tagged once."""
    global_timer.reset()
    global_timer.enable()
    try:
        rng = np.random.RandomState(0)
        X = rng.rand(500, 4)
        y = (X[:, 0] > 0.5).astype(np.float32)
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=3)
        items = global_timer.items()
        for key in ("TreeLearner::Train(dispatch)",
                    "GBDT::FinishIter(host trees)"):
            assert key in items, (key, sorted(items))
        assert items["TreeLearner::Train(dispatch)"][0] == 2
    finally:
        global_timer.disable()
        global_timer.reset()
