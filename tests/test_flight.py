"""Active observability layer (ISSUE 11, docs/OBSERVABILITY.md):
flight recorder forensic bundles, SLO watchdog, pod-level telemetry
aggregation, bottleneck diagnosis, and metrics-over-HTTP.

Acceptance bars covered here:
- a chaos-injected CollectiveError and a serving quarantine each produce
  a parseable forensic bundle (Chrome-trace ring + metrics snapshot +
  config/env/mesh fingerprint) WITHOUT crashing the host process;
- a simulated stall breaches the watchdog (slo_breach_total) and dumps;
- with the recorder armed, trained model text is byte-identical and the
  recording overhead is way inside the <1% budget;
- obs_doctor names the injected bottleneck for the three canonical
  scenarios (DCN-heavy reduction, cold compile cache, throttled pump).
"""

import glob
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.flight import FlightRecorder, global_flight
from lightgbm_tpu.obs.metrics import MetricsRegistry, global_registry
from lightgbm_tpu.obs.watchdog import (SLOConfig, Watchdog,
                                       histogram_p99_ms)

pytestmark = pytest.mark.obs


@pytest.fixture
def flight_dir(tmp_path, monkeypatch):
    """Point the PROCESS flight recorder at a scratch dir with a fresh
    dump budget; restore afterwards."""
    monkeypatch.setattr(global_flight, "_out_dir", str(tmp_path))
    monkeypatch.setattr(global_flight, "dumps", 0)
    monkeypatch.setattr(global_flight, "enabled", True)
    return tmp_path


def _bundles(d, pat="flight_*.json"):
    return sorted(glob.glob(os.path.join(str(d), pat)))


def _check_bundle(path):
    """The bundle contract: one JSON file whose ring is a loadable
    Chrome trace and whose metrics section is a registry snapshot."""
    with open(path) as fh:
        b = json.load(fh)
    assert b["flight_bundle"] >= 1
    evs = b["ring"]["traceEvents"]
    assert isinstance(evs, list) and evs
    assert evs[0]["ph"] == "M"                      # process metadata
    body = [e for e in evs[1:]]
    ts = [e["ts"] for e in body]
    assert ts == sorted(ts)                         # timestamp-sorted
    for e in body:
        assert e["ph"] in ("X", "i") and "pid" in e and "tid" in e
    assert "counters" in b["metrics"] and "gauges" in b["metrics"]
    fp = b["fingerprint"]
    assert fp["pid"] == os.getpid()
    assert "env" in fp and "python" in fp
    return b


# ------------------------------------------------------------ ring basics


def test_flight_ring_is_bounded():
    fr = FlightRecorder(max_events=64, enabled=True, max_dumps=0)
    for i in range(1000):
        fr.note("tick", i=i)
    evs = fr.ring_events()
    assert len(evs) == 64                 # O(1) memory: deque maxlen
    assert evs[-1]["args"]["i"] == 999    # newest survive, oldest roll


def test_flight_disabled_records_and_dumps_nothing(tmp_path):
    fr = FlightRecorder(enabled=False, out_dir=str(tmp_path))
    fr.note("x")
    fr.feed({"name": "y", "ph": "i", "ts": 0.0})
    assert fr.ring_events() == []
    assert fr.dump("manual") is None
    assert _bundles(tmp_path) == []


def test_flight_manual_dump_bundle(tmp_path):
    fr = FlightRecorder(max_events=32, enabled=True, out_dir=str(tmp_path))
    fr.set_context(phase="test", rows=123)
    for i in range(5):
        fr.note("step", i=i, dur_us=10.0)
    fr.note_instant("planner.plan", {"variant": "matmul"})
    p = fr.dump("manual", extra={"note": "hello"})
    assert p is not None and os.path.exists(p)
    b = _check_bundle(p)
    assert b["trigger"] == "manual"
    assert b["fingerprint"]["context"]["phase"] == "test"
    assert b["extra"]["note"] == "hello"
    names = [e["name"] for e in b["ring"]["traceEvents"]]
    assert "step" in names and "planner.plan" in names


def test_flight_dump_rate_limit(tmp_path):
    fr = FlightRecorder(enabled=True, out_dir=str(tmp_path), max_dumps=2)
    assert fr.dump("a") and fr.dump("b")
    assert fr.dump("c") is None           # budget spent: no dump storm
    assert len(_bundles(tmp_path)) == 2


def test_flight_metric_deltas():
    fr = FlightRecorder(enabled=True, max_dumps=0)
    reg = MetricsRegistry()
    reg.counter("widgets_total").inc(3)
    fr.sample_metrics(reg, min_interval_s=0.0)
    reg.counter("widgets_total").inc(4)
    fr.sample_metrics(reg, min_interval_s=0.0)
    d = fr._metric_deltas()
    assert d["deltas"]["widgets_total"] == 4


# ------------------------------------------------- failure-trigger dumps


@pytest.mark.chaos
def test_collective_error_dumps_forensic_bundle(flight_dir):
    """The chaos seam (ChaosRegistry) injects a persistent per-rank
    corruption; the rank-consistent abort must leave a parseable bundle
    per rank and the host process keeps running."""
    from lightgbm_tpu.parallel.dist_data import make_fake_allgather
    from lightgbm_tpu.resilience import (ChaosRegistry, ResilienceConfig,
                                         resilient_allgather)
    from lightgbm_tpu.resilience.retry import CollectiveError

    world = 2
    # bit-flip EVERY round rank 1 sends (payload and verdict frames
    # alike) so no attempt can ever commit -> retries exhausted
    chaos = ChaosRegistry(",".join(
        f"allgather.bitflip@{i}:rank=1" for i in range(12)), seed=0)
    fake = make_fake_allgather(world, timeout=2.0)
    cfg = ResilienceConfig(deadline_s=8.0, max_retries=1,
                           base_backoff_s=0.01)
    errs = [None] * world

    def runner(k):
        try:
            resilient_allgather(
                b"payload", chaos.wrap_allgather(fake(k), k),
                world=world, rank=k, config=cfg)
        except Exception as e:  # noqa: BLE001
            errs[k] = e

    threads = [threading.Thread(target=runner, args=(k,))
               for k in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert all(isinstance(e, CollectiveError) for e in errs), errs
    bundles = _bundles(flight_dir, "flight_collective_*.json")
    assert bundles, "no forensic bundle for the collective abort"
    b = _check_bundle(bundles[0])
    assert b["exception"]["type"] == "CollectiveError"
    # the ring shows the retry ladder even with tracing off
    atts = [e for e in b["ring"]["traceEvents"]
            if e["name"] == "allgather.attempt"]
    assert atts and any(not a["args"]["committed"] for a in atts)


def test_serving_quarantine_dumps_forensic_bundle(flight_dir):
    """A low-precision candidate over its accuracy budget is quarantined
    at admission; the quarantine leaves a bundle and the caller gets the
    typed error, not a dead process."""
    from lightgbm_tpu.serving.errors import LowPrecisionQuarantined

    rng = np.random.RandomState(0)
    X = rng.rand(400, 5)
    y = (X[:, 0] > 0.5).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 5)
    with pytest.raises(LowPrecisionQuarantined):
        bst.serve(backend="host", precision="int8", accuracy_budget=0.0)
    bundles = _bundles(flight_dir, "flight_serving.swap_*.json")
    assert bundles, "no forensic bundle for the quarantine"
    b = _check_bundle(bundles[0])
    assert b["exception"]["type"] == "LowPrecisionQuarantined"
    assert b["extra"]["precision"] == "int8"


def test_engine_loop_exception_dumps_bundle(flight_dir):
    rng = np.random.RandomState(0)
    X = rng.rand(300, 4)
    y = (X[:, 0] > 0.5).astype(np.float32)

    def exploding_fobj(preds, ds):
        raise RuntimeError("boom at iteration 0")

    with pytest.raises(RuntimeError):
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1}, lgb.Dataset(X, label=y), 3,
                  fobj=exploding_fobj)
    bundles = _bundles(flight_dir, "flight_engine.train_*.json")
    assert bundles
    b = _check_bundle(bundles[0])
    assert b["exception"]["type"] == "RuntimeError"
    assert b["fingerprint"]["context"]["phase"] == "train"


def test_slice_lost_dumps_bundle(flight_dir):
    """A failed membership probe (dead transport) raises SliceLostError
    AND leaves the elastic bundle."""
    from lightgbm_tpu.resilience import ResilienceConfig
    from lightgbm_tpu.resilience.elastic import (SliceLostError,
                                                 membership_probe)

    def dead_transport(payload):
        raise OSError("host unreachable")

    with pytest.raises(SliceLostError):
        membership_probe(dead_transport, world=2, rank=0,
                         config=ResilienceConfig(deadline_s=0.5,
                                                 max_retries=0,
                                                 base_backoff_s=0.01))
    assert _bundles(flight_dir, "flight_elastic.membership_*.json")


# --------------------------------------------------------------- watchdog


def test_watchdog_stall_breach_and_dump(tmp_path):
    fl = FlightRecorder(enabled=True, out_dir=str(tmp_path))
    reg = MetricsRegistry()
    wd = Watchdog(SLOConfig(heartbeat_stale_s=0.05), registry=reg,
                  flight=fl)
    wd.watch_heartbeat("engine.step")
    time.sleep(0.12)
    breaches = wd.check_once()
    assert [b[0] for b in breaches] == ["stall:engine.step"]
    key = 'slo_breach_total{slo="stall:engine.step"}'
    assert reg.to_dict()["counters"][key] == 1
    assert _bundles(tmp_path, "flight_watchdog_*.json")
    # persistent breach: counter keeps counting, dump only on the edge
    n = len(_bundles(tmp_path))
    wd.check_once()
    assert reg.to_dict()["counters"][key] == 2
    assert len(_bundles(tmp_path)) == n
    # recovery clears the edge so a NEW stall dumps again
    wd.beat("engine.step")
    assert wd.check_once() == []


def test_watchdog_unwatch_stops_stall_checks():
    wd = Watchdog(SLOConfig(heartbeat_stale_s=0.01),
                  registry=MetricsRegistry(),
                  flight=FlightRecorder(enabled=False))
    wd.watch_heartbeat("loop")
    wd.unwatch("loop")
    time.sleep(0.03)
    assert wd.check_once() == []      # a FINISHED loop never breaches


def test_watchdog_rate_floor():
    reg = MetricsRegistry()
    wd = Watchdog(SLOConfig(heartbeat_stale_s=100.0,
                            trees_per_sec_floor=50.0),
                  registry=reg, flight=FlightRecorder(enabled=False))
    wd.watch_heartbeat("engine.step", floor=50.0)
    wd._beats["engine.step"] = (100.0, 0)
    wd._rate_state["engine.step"] = (100.0, 0)
    # 10 trees over 1s = 10/s < floor 50/s -> breach
    wd._beats["engine.step"] = (101.0, 10)
    breaches = wd.check_once(now=101.0)
    assert [b[0] for b in breaches] == ["slo:engine.step"]
    assert breaches[0][1]["rate"] == 10.0
    # 100 trees over the next 1s -> healthy again
    wd._beats["engine.step"] = (102.0, 110)
    assert wd.check_once(now=102.0) == []


def test_watchdog_serving_p99_ceiling():
    reg = MetricsRegistry()
    hist = reg.histogram("request_latency_ms")
    for _ in range(100):
        hist.observe(3.0)
    assert histogram_p99_ms(hist) == 5.0       # bucket upper bound
    wd = Watchdog(SLOConfig(serving_p99_ms=100.0), registry=reg,
                  flight=FlightRecorder(enabled=False))
    wd.watch_histogram_p99("serving", hist)
    assert wd.check_once() == []               # p99 ~5ms under 100ms
    for _ in range(100):
        hist.observe(900.0)
    breaches = wd.check_once()
    assert [b[0] for b in breaches] == ["slo:serving"]
    assert breaches[0][1]["p99_ms"] > 100.0


def test_watchdog_sentry_thread_runs_checks(tmp_path):
    fl = FlightRecorder(enabled=True, out_dir=str(tmp_path))
    wd = Watchdog(SLOConfig(heartbeat_stale_s=0.03,
                            check_interval_s=0.01),
                  registry=MetricsRegistry(), flight=fl)
    wd.watch_heartbeat("x")
    wd.start()
    try:
        deadline = time.time() + 2.0
        while time.time() < deadline and not _bundles(tmp_path):
            time.sleep(0.02)
    finally:
        wd.stop()
    assert not wd.running
    assert _bundles(tmp_path, "flight_watchdog_stall_x*.json")


# --------------------------------------------------- A/B recorder guard


def test_recorder_on_model_byte_identical_and_cheap(tmp_path):
    """The acceptance A/B: arming the recorder must not change a single
    byte of the model.  The <1% overhead budget is asserted where it is
    measurable deterministically: per-event recording cost vs per-
    iteration cost (wall-clock A/B of two short trainings is dominated
    by compile/jitter noise, not by the recorder)."""
    rng = np.random.RandomState(7)
    X = rng.rand(2000, 6)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    P = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "deterministic": True}

    def run(enabled):
        was = global_flight.enabled
        global_flight.enabled = enabled
        try:
            bst = lgb.train(P, lgb.Dataset(X, label=y), 8,
                            verbose_eval=False)
            return bst.model_to_string()
        finally:
            global_flight.enabled = was

    assert run(True) == run(False)      # byte-identical model text
    # recording cost: a note is O(µs); even a 10ms iteration gives the
    # recorder (1 note + 2 gauge sets + 1 beat per step) <1% headroom
    fr = FlightRecorder(max_events=2048, enabled=True, max_dumps=0)
    t0 = time.perf_counter()
    for i in range(10_000):
        fr.note("engine.step", i=i, dur_us=1.0)
    per_note_s = (time.perf_counter() - t0) / 10_000
    assert per_note_s < 50e-6, f"note() costs {per_note_s * 1e6:.1f}us"


# ----------------------------------------------------- pod aggregation


def test_pod_vector_roundtrip():
    from lightgbm_tpu.obs.aggregate import (pack_rank_vector,
                                            unpack_rank_vector)
    rank, vals = unpack_rank_vector(pack_rank_vector(
        {"iter_seconds": 1.5, "dcn_payload_bytes": 4096.0}, rank=3))
    assert rank == 3
    assert vals["iter_seconds"] == 1.5
    assert vals["dcn_payload_bytes"] == 4096.0
    assert vals["mfu"] == 0.0                      # absent slot -> 0
    with pytest.raises(ValueError):
        unpack_rank_vector(b"garbage-frame-bytes")


def test_pod_gather_derives_straggler_and_sums():
    """4 ranks / 2 slices through the resilient fake transport: every
    rank converges on the same pod view; slice 1 (ranks 2,3) is the
    straggler."""
    from lightgbm_tpu.obs.aggregate import gather_pod_metrics
    from lightgbm_tpu.parallel.dist_data import make_fake_allgather
    from lightgbm_tpu.resilience import ResilienceConfig

    world = 4
    fake = make_fake_allgather(world, timeout=5.0)
    regs = [MetricsRegistry() for _ in range(world)]
    views, errs = [None] * world, [None] * world

    def runner(k):
        try:
            views[k] = gather_pod_metrics(
                fake(k), world=world, rank=k, num_slices=2,
                registry=regs[k],
                config=ResilienceConfig(deadline_s=10.0, max_retries=2),
                values={"iter_seconds": 1.0 if k < 2 else 2.0,
                        "ici_payload_bytes": 100.0,
                        "dcn_payload_bytes": 10.0,
                        "mfu": 0.004})
        except Exception as e:  # noqa: BLE001
            errs[k] = e

    threads = [threading.Thread(target=runner, args=(k,))
               for k in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert errs == [None] * world
    for k, v in enumerate(views):
        assert v.world == 4 and v.num_slices == 2
        assert v.straggler_slice == 1
        assert v.straggler_skew == pytest.approx(2.0)
        assert v.pod_ici_payload_bytes == 400.0
        assert v.pod_dcn_payload_bytes == 40.0
        assert v.pod_mfu == pytest.approx(0.004)
        g = regs[k].to_dict()["gauges"]
        assert g["pod_straggler_slice"] == 1
        assert g["pod_straggler_skew"] == 2.0
        assert g["pod_world"] == 4


def test_engine_eval_boundary_gathers_when_transport_registered():
    """The engine's eval-boundary hook runs a real telemetry round when
    a pod transport is registered (world=1 self-gather here), and is a
    no-op otherwise."""
    from lightgbm_tpu.obs import aggregate
    from lightgbm_tpu.parallel.dist_data import make_fake_allgather

    rng = np.random.RandomState(0)
    X = rng.rand(400, 4)
    y = (X[:, 0] > 0.5).astype(np.float32)
    assert aggregate.maybe_gather_at_eval() is None     # no transport
    fake = make_fake_allgather(1, timeout=5.0)
    aggregate.register_pod_transport(fake(0), world=1, rank=0,
                                     num_slices=1)
    try:
        ds = lgb.Dataset(X, label=y)
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1, "metric": "binary_logloss"},
                  ds, 2, valid_sets=[ds], verbose_eval=False)
        g = global_registry.to_dict()["gauges"]
        assert g.get("pod_world") == 1
    finally:
        aggregate.clear_pod_transport()
    assert aggregate.maybe_gather_at_eval() is None


# ----------------------------------------------------------- diagnosis


def _diag_top(signals):
    from lightgbm_tpu.obs.diagnose import diagnose
    return diagnose(signals)[0]


def test_doctor_names_dcn_bound():
    """Forced-hierarchical DCN-heavy reduction: 2 GB crossing a
    6.25 GB/s DCN each sync vs a 1 s iteration -> DCN-bound."""
    v = _diag_top({"train_dcn_payload_bytes": 2e9,
                   "train_num_slices": 4, "train_hier_reduce": 1,
                   "train_iter_seconds": 1.0, "dcn_gbps": 6.25})
    assert v.name == "dcn-bound"
    assert v.evidence["num_slices"] == 4
    assert v.evidence["fraction"] > 0.25


def test_doctor_names_compile_bound():
    """Cold compile cache: 130 s compiling vs 25 s training (the r5
    figure) -> compile-bound."""
    v = _diag_top({"compile_seconds": 130.0, "train_seconds": 25.0,
                   "compile_cache_warm": 0})
    assert v.name == "compile-bound"
    assert v.evidence["compile_cache_warm"] is False
    assert v.score > 0.8


def test_doctor_names_input_bound():
    """Throttled stream pump: overlap efficiency ~1.0 means device_put
    is never hidden -> input-bound."""
    v = _diag_top({"stream_blocks_total": 64, "overlap_efficiency": 1.0})
    assert v.name == "input-bound"
    assert v.evidence["overlap_efficiency"] == 1.0


def test_doctor_names_straggler_and_kernel():
    v = _diag_top({"pod_straggler_skew": 1.8, "pod_straggler_slice": 2})
    assert v.name == "straggler" and v.evidence["straggler_slice"] == 2
    v = _diag_top({"mfu_measured_best": 0.0005})
    assert v.name == "kernel-underutilized"
    v = _diag_top({})
    assert v.name == "healthy"


def test_doctor_ranks_verdicts():
    from lightgbm_tpu.obs.diagnose import diagnose
    vs = diagnose({"compile_seconds": 130.0, "train_seconds": 25.0,
                   "train_dcn_payload_bytes": 3e8,
                   "train_num_slices": 2, "train_iter_seconds": 0.15,
                   "dcn_gbps": 6.25})
    names = [v.name for v in vs]
    assert set(names) == {"compile-bound", "dcn-bound"}
    assert [v.score for v in vs] == sorted(
        (v.score for v in vs), reverse=True)


def test_doctor_collects_from_journal_stages():
    """collect_signals joins banked bench stages (full/stream_probe/
    collective_probe) with registry gauges; run_doctor produces the
    journal-ready report naming the injected bottleneck."""
    from lightgbm_tpu.obs.diagnose import run_doctor

    stages = {
        "full@200000": {"sec_per_tree": 0.5, "value": 25.0,
                        "compile_seconds": 130.0, "trees": 50,
                        "compile_cache": {"warm_start": False},
                        "mfu_measured": {"f32/matmul/untiled":
                                         {"mfu": 0.002}}},
        "stream_probe": {"overlap_efficiency": 1.0},
    }
    report = run_doctor(registry=MetricsRegistry(), stages=stages)
    assert report["top_verdict"] == "compile-bound"
    names = [v["name"] for v in report["verdicts"]]
    assert "input-bound" in names
    assert report["signals"]["mfu_measured_best"] == 0.002
    json.dumps(report)          # journal-ready


def test_obs_doctor_tool(tmp_path):
    """The CLI: journal in, human table + machine-readable last line
    out."""
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    journal = tmp_path / "j.json"
    journal.write_text(json.dumps({
        "fingerprint": "t", "stages": {
            "full": {"compile_seconds": 130.0, "value": 25.0,
                     "compile_cache": {"warm_start": False}}}}))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "obs_doctor.py"),
         "--journal", str(journal), "--metrics", str(tmp_path / "no")],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = proc.stdout.strip().splitlines()
    report = json.loads(lines[-1])
    assert report["top_verdict"] == "compile-bound"
    assert "compile-bound" in proc.stdout


# -------------------------------------------------------- HTTP endpoint


def test_metrics_http_endpoint():
    from lightgbm_tpu.obs.http import MetricsHTTPServer

    reg = MetricsRegistry()
    reg.counter("requests_total").inc(7)
    reg.gauge("depth").set(3)
    reg.histogram("lat_ms").observe(2.0)
    srv = MetricsHTTPServer(registry=reg, port=0)
    try:
        port = srv.start()
        base = f"http://127.0.0.1:{port}"
        prom = urllib.request.urlopen(f"{base}/metrics",
                                      timeout=5).read().decode()
        assert "# TYPE lgbt_requests_total counter" in prom
        assert "lgbt_requests_total 7" in prom
        snap = json.loads(urllib.request.urlopen(
            f"{base}/metrics.json", timeout=5).read())
        assert snap["counters"]["requests_total"] == 7
        assert snap["gauges"]["depth"] == 3
        hz = urllib.request.urlopen(f"{base}/healthz", timeout=5).read()
        assert hz == b"ok\n"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=5)
    finally:
        srv.stop()


def test_metrics_http_env_gate(monkeypatch):
    from lightgbm_tpu.obs import http as obs_http

    monkeypatch.delenv("LIGHTGBM_TPU_METRICS_PORT", raising=False)
    obs_http.stop_process_server()
    assert obs_http.maybe_start_from_env() is None       # opt-in only
    monkeypatch.setenv("LIGHTGBM_TPU_METRICS_PORT", "0")
    try:
        srv = obs_http.maybe_start_from_env()
        assert srv is not None and srv.port > 0
        assert obs_http.maybe_start_from_env() is srv    # idempotent
        prom = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics",
            timeout=5).read().decode()
        assert "# TYPE" in prom or prom == "\n"
    finally:
        obs_http.stop_process_server()


# ------------------------------------------------------ trace event cap


def test_tracer_caps_events_and_counts_drops():
    from lightgbm_tpu.obs.trace import Tracer

    t = Tracer(enabled=True, max_events=10)
    for i in range(25):
        with t.span("s", i=i):
            pass
    assert len(t.events()) == 10          # bounded in-process list
    assert t.dropped == 15
    doc = t.to_chrome_trace()
    tail = doc["traceEvents"][-1]
    assert tail["name"] == "trace_events_dropped"
    assert tail["args"]["dropped"] == 15
    assert global_registry.to_dict()["gauges"][
        "trace_events_dropped"] >= 15
    t.reset()
    assert t.dropped == 0 and t.events() == []


def test_tracer_cap_env(monkeypatch):
    from lightgbm_tpu.obs.trace import Tracer

    monkeypatch.setenv("LIGHTGBM_TPU_TRACE_MAX_EVENTS", "5")
    t = Tracer(enabled=True)
    assert t.max_events == 5
    monkeypatch.setenv("LIGHTGBM_TPU_TRACE_MAX_EVENTS", "junk")
    assert Tracer(enabled=True).max_events > 5          # fallback


def test_flight_ring_sees_training_without_tracing(flight_dir):
    """The whole point of always-on: with LIGHTGBM_TPU_TRACE unset the
    tracer records nothing, yet the ring still holds the step/planner
    history a bundle needs."""
    from lightgbm_tpu.obs.trace import global_tracer

    assert not global_tracer.enabled
    rng = np.random.RandomState(0)
    X = rng.rand(400, 4)
    y = (X[:, 0] > 0.5).astype(np.float32)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
              lgb.Dataset(X, label=y), 3)
    assert global_tracer.events() == []
    names = {e["name"] for e in global_flight.ring_events()}
    assert "engine.step" in names
    assert "planner.plan" in names
