"""HBM budget planner (lightgbm_tpu/ops/planner.py).

Planning runs against a FAKE memory model (``budget_bytes`` /
``LGBM_TPU_HBM_BYTES``) so the verdicts are deterministic off-TPU: the
r5 OOM shape must become a planned, feasible run; small shapes must stay
untiled; the int16 psum narrowing decision must match the kernel-side
static bound; and the predicted peak must track reality on a
scaled-down shape (the off-TPU acceptance path).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.ops.planner import (DEFAULT_HBM_BYTES, MIN_TILE_ROWS,
                                      HistPlan, apply_plan,
                                      hbm_limit_bytes, plan_histograms,
                                      predict_peak_bytes)

GB = 1 << 30


def test_small_shape_stays_untiled():
    p = plan_histograms(100_000, 28, 64, num_leaves=63,
                        budget_bytes=16 * GB, accel=True)
    assert p.tile_rows == 0 and p.use_pack
    assert p.feasible and not p.degraded


def test_r5_oom_shape_becomes_planned_run():
    """The exact shape that died in r5 (>=10M x 28, 255 leaves, B=64,
    157.7 GB requested vs ~17 GB HBM): the untiled prediction must land
    in the measured order of magnitude, and the plan must degrade to a
    power-of-two tile whose predicted peak fits a 16 GB budget."""
    p = plan_histograms(11_000_000, 28, 64, num_leaves=255,
                        budget_bytes=16 * GB, accel=True)
    # the unplanned pipeline wildly exceeds HBM (r5 measured 157.7 GB)
    assert p.untiled_peak_bytes > 100 * GB
    assert p.degraded and p.feasible
    assert p.tile_rows >= MIN_TILE_ROWS
    assert p.tile_rows & (p.tile_rows - 1) == 0        # power of two
    assert not p.use_pack     # no whole-dataset record arena when tiled
    assert p.predicted_peak_bytes <= p.budget_bytes
    # 10M flavor of the acceptance shape
    p10 = plan_histograms(10_000_000, 28, 64, num_leaves=255,
                          budget_bytes=16 * GB, accel=True)
    assert p10.feasible


def test_infeasible_verdict():
    p = plan_histograms(11_000_000, 28, 64, num_leaves=255,
                        budget_bytes=256 << 20, accel=True)
    assert not p.feasible
    assert p.tile_rows == MIN_TILE_ROWS    # degraded to the floor


def test_peak_monotone_in_tile():
    for variant in ("scatter", "sorted", "matmul"):
        peaks = [predict_peak_bytes(4_000_000, 28, 64, num_leaves=255,
                                    variant=variant, tile_rows=t,
                                    use_pack=(t == 0), accel=True)[0]
                 for t in (0, 1 << 21, 1 << 18, 1 << 16)]
        assert peaks == sorted(peaks, reverse=True), (variant, peaks)


def test_narrowing_decision_matches_kernel_bound():
    from lightgbm_tpu.ops.histogram import quant_psum_narrow
    for rows, bins in ((1_000, 4), (200_000, 4), (1_000_000, 64)):
        p = plan_histograms(rows, 28, 64, quant=True, quant_bins=bins,
                            budget_bytes=16 * GB)
        assert p.narrow_int16 == quant_psum_narrow(rows, bins)


def test_env_overrides(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_TILE_ROWS", "262144")
    p = plan_histograms(11_000_000, 28, 64, num_leaves=255,
                        budget_bytes=16 * GB, accel=True)
    assert p.tile_rows == 262144 and not p.use_pack and not p.degraded
    monkeypatch.setenv("LGBM_TPU_TILE_ROWS", "off")
    p = plan_histograms(11_000_000, 28, 64, num_leaves=255,
                        budget_bytes=16 * GB, accel=True)
    assert p.tile_rows == 0
    monkeypatch.delenv("LGBM_TPU_TILE_ROWS")
    monkeypatch.setenv("LGBM_TPU_HBM_BYTES", str(8 * GB))
    limit, source = hbm_limit_bytes()
    assert limit == 8 * GB and source == "env"


def test_limit_fallback_has_source():
    limit, source = hbm_limit_bytes()
    assert limit > 0 and source in ("memory_stats", "env", "default")
    if source == "default":
        assert limit == DEFAULT_HBM_BYTES


def test_apply_plan_threads_config(monkeypatch):
    from lightgbm_tpu.grower import GrowerConfig
    monkeypatch.setenv("LGBM_TPU_HBM_BYTES", str(16 * GB))
    cfg, plan = apply_plan(GrowerConfig(num_leaves=63, num_bins=64),
                           100_000, 28)
    assert isinstance(plan, HistPlan)
    assert cfg.tile_rows == plan.tile_rows
    # a tiny fake budget forces tiling + clears the record-arena hoist
    monkeypatch.setenv("LGBM_TPU_HBM_BYTES", str(64 << 20))
    cfg, plan = apply_plan(
        GrowerConfig(num_leaves=255, num_bins=64), 4_000_000, 28,
        accel=True)
    assert plan.degraded and cfg.tile_rows > 0 and not cfg.hist_pack


def test_summary_is_json_ready():
    import json
    p = plan_histograms(1_000_000, 28, 64, budget_bytes=16 * GB)
    d = json.loads(json.dumps(p.summary()))
    assert d["hbm_limit_bytes"] == p.limit_bytes
    assert set(d) >= {"tile_rows", "feasible", "predicted_peak_bytes",
                      "untiled_peak_bytes", "degraded", "variant"}


def test_prediction_tracks_measured_lower_bound():
    """Off-TPU acceptance path: on a scaled-down shape the predicted
    peak must be at least the bytes of the arrays the pipeline REALLY
    allocates (binned matrix + hist cache + update buffer) and within a
    small factor of that floor — i.e. the model is anchored to reality,
    not a fudge constant."""
    rows, F, B, L = 200_000, 28, 64, 255
    floor = (rows * F                  # binned u8
             + L * 3 * F * B * 4       # hist cache f32
             + rows * F * 3 * 4)       # untiled scatter updates
    pred = predict_peak_bytes(rows, F, B, num_leaves=L, variant="scatter",
                              tile_rows=0, accel=False)[0]
    assert floor <= pred <= 12 * floor
    # tiled: the update buffer leaves the model, the residents remain
    pred_t = predict_peak_bytes(rows, F, B, num_leaves=L,
                                variant="scatter", tile_rows=1 << 16,
                                use_pack=False, accel=False)[0]
    floor_t = rows * F + L * 3 * F * B * 4
    assert floor_t <= pred_t < pred


def test_booster_exposes_plan(monkeypatch):
    """The GBDT layer plans at build time and a forced tile flows into
    the grower config (end-to-end threading check)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.randn(600, 6)
    y = (X[:, 0] > 0).astype(float)
    monkeypatch.setenv("LGBM_TPU_TILE_ROWS", "128")
    b = lgb.Booster(params={"objective": "binary", "verbosity": -1,
                            "num_leaves": 7},
                    train_set=lgb.Dataset(X, label=y, free_raw_data=False))
    plan = b.boosting.hist_plan
    assert plan.tile_rows == 128
    assert b.boosting.grower_cfg.tile_rows == 128
    assert not b.boosting.grower_cfg.hist_pack
    b.update()
    assert b.boosting.iter == 1


# ======================================================================
# Measured-timings autotuner + shape-bucket ladder (the compile-time war)

def test_bucket_rows_ladder():
    """Rungs are {2^k, 1.5*2^k} with a 4096 floor: pad waste is bounded
    at 50% and every rung maps to itself (idempotent)."""
    from lightgbm_tpu.ops.planner import MIN_BUCKET_ROWS, bucket_rows
    assert bucket_rows(1) == MIN_BUCKET_ROWS
    assert bucket_rows(4096) == 4096
    assert bucket_rows(4097) == 6144
    assert bucket_rows(6145) == 8192
    assert bucket_rows(1_000_001) == 1 << 20
    assert bucket_rows(1_100_000) == (1 << 20) + (1 << 19)
    for n in (4096, 6144, 8192, 12288, 1 << 20):
        assert bucket_rows(n) == n
        assert bucket_rows(bucket_rows(n + 1)) == bucket_rows(n + 1)


def test_autotune_warm_election_flip_and_promotion(tmp_path, monkeypatch):
    """Cold query = analytic + a miss; banked measurements flip the
    election to the stopwatch's winner; apply_plan promotes a measured
    point method into the grower config."""
    from lightgbm_tpu.grower import GrowerConfig
    from lightgbm_tpu.ops import planner as P
    monkeypatch.setenv("LGBM_TPU_AUTOTUNE_DIR", str(tmp_path))
    shape = (50_000, 12, 64, True, 8)   # rows, F, B, quant, round_width
    P.autotune_counters(reset=True)
    plan = P.plan_histograms(50_000, 12, 64, quant=True, method="auto",
                             round_width=8)
    assert plan.elected_by == "analytic"
    assert P.autotune_counters()["misses"] == 1
    # bank measurements: matmul_int8 fastest (differs from the CPU
    # analytic scatter_int, so the adoption is also a FLIP)
    assert P.record_timing(*shape, "scatter_int", 0.05) is not None
    assert P.record_timing(*shape, "matmul_int8", 0.01) is not None
    plan2 = P.plan_histograms(50_000, 12, 64, quant=True, method="auto",
                              round_width=8)
    assert plan2.elected_by == "measured"
    assert plan2.variant == "matmul_int8"
    assert plan2.measured_variant == "matmul_int8"
    assert plan2.autotune_key == P.shape_bucket_key(*shape)
    c = P.autotune_counters()
    assert c["hits"] == 1 and c["misses"] == 1 and c["flips"] == 1
    last = P.autotune_last()
    assert last["elected_by"] == "measured"
    assert last["elected_variant"] == "matmul_int8"
    # a row count in the SAME bucket reuses the measurement (the whole
    # point of bucketed keys: exact-shape keys would never warm up)
    plan3 = P.plan_histograms(50_001, 12, 64, quant=True, method="auto",
                              round_width=8)
    assert plan3.elected_by == "measured"
    # apply_plan promotes the measured POINT method into hist_method
    cfg = GrowerConfig(num_leaves=15, num_bins=64, round_width=8,
                       hist_method="auto", quant=True, quant_bins=8)
    cfg2, _ = apply_plan(cfg, 50_000, 12)
    assert cfg2.hist_method == "matmul_int8"
    # an explicit method ignores the store entirely
    plan4 = P.plan_histograms(50_000, 12, 64, quant=True,
                              method="scatter_int", round_width=8)
    assert plan4.elected_by == "analytic"


def test_autotune_measured_staged_family_verdict(tmp_path, monkeypatch):
    """A "staged" family verdict declines fused even when its arena
    fits; a "fused" verdict only adopts when the VMEM election passed,
    and measured kernel params override the analytic walk."""
    from lightgbm_tpu.ops import planner as P
    monkeypatch.setenv("LGBM_TPU_AUTOTUNE_DIR", str(tmp_path))
    shape = (40_000, 8, 64, False, 8)
    P.record_timing(*shape, "fused", 0.01,
                    params={"feat_tile": 2, "block_rows": 128})
    plan = P.plan_histograms(40_000, 8, 64, method="auto", round_width=8,
                             fused_ok=True)
    assert plan.fused and plan.elected_by == "measured"
    assert plan.fused_feat_tile == 2 and plan.fused_block_rows == 128
    # staged measured faster -> fused declined though the arena fits
    P.record_timing(*shape, "staged", 0.001)
    plan2 = P.plan_histograms(40_000, 8, 64, method="auto", round_width=8,
                              fused_ok=True)
    assert not plan2.fused and plan2.elected_by == "measured"
    assert plan2.variant != "fused"
    # without fused_ok the "fused" record cannot be adopted (no VMEM
    # election ran) -> miss, analytic
    (tmp_path / "hist_timings.json").unlink()
    P.record_timing(*shape, "fused", 0.01)
    plan3 = P.plan_histograms(40_000, 8, 64, method="auto", round_width=8)
    assert plan3.elected_by == "analytic" and not plan3.fused


def test_autotune_corrupt_store_is_a_miss(tmp_path, monkeypatch):
    """Satellite: a corrupt, truncated, wrong-version or stale-named
    store entry is a MISS, never a crash — and the next record_timing
    rewrites a clean store through write_atomic."""
    import json as _json
    from lightgbm_tpu.ops import planner as P
    monkeypatch.setenv("LGBM_TPU_AUTOTUNE_DIR", str(tmp_path))
    store = tmp_path / "hist_timings.json"
    shape = (50_000, 12, 64, True, 8)
    for garbage in ("{not json", "", "[1, 2, 3]",
                    _json.dumps({"version": 999, "entries": {
                        P.shape_bucket_key(*shape): {
                            "scatter_int": {"seconds": 0.01}}}}),
                    _json.dumps({"version": 1, "entries": "nope"})):
        store.write_text(garbage, encoding="utf-8")
        assert P.measured_election(*shape) is None
        P.autotune_counters(reset=True)
        plan = P.plan_histograms(50_000, 12, 64, quant=True,
                                 method="auto", round_width=8)
        assert plan.elected_by == "analytic", garbage[:20]
        assert P.autotune_counters()["misses"] == 1
    # a stale variant NAME inside a well-formed store is also a miss
    store.write_text(_json.dumps({"version": 1, "entries": {
        P.shape_bucket_key(*shape): {
            "kernel_deleted_in_pr9": {"seconds": 0.001}}}}),
        encoding="utf-8")
    P.autotune_counters(reset=True)
    plan = P.plan_histograms(50_000, 12, 64, quant=True, method="auto",
                             round_width=8)
    assert plan.elected_by == "analytic"
    assert P.autotune_counters() == {"hits": 0, "misses": 1, "flips": 0}
    # recovery: record_timing read-merges {} from the bad store and
    # lands a clean versioned document atomically
    store.write_text("{torn", encoding="utf-8")
    P.record_timing(*shape, "scatter_int", 0.02)
    doc = _json.loads(store.read_text(encoding="utf-8"))
    assert doc["version"] == 1
    assert P.measured_election(*shape)["variant"] == "scatter_int"


def test_autotune_disabled_and_no_store(monkeypatch):
    """LGBM_TPU_AUTOTUNE=0 skips the election entirely; with no store
    dir configured record_timing is a no-op and elections are cold."""
    from lightgbm_tpu.ops import planner as P
    monkeypatch.delenv("LGBM_TPU_AUTOTUNE_DIR", raising=False)
    monkeypatch.delenv("LGBM_TPU_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    assert P.record_timing(10_000, 8, 64, False, 8, "scatter", 0.01) is None
    assert P.measured_election(10_000, 8, 64, False, 8) is None
    monkeypatch.setenv("LGBM_TPU_AUTOTUNE", "0")
    P.autotune_counters(reset=True)
    plan = P.plan_histograms(10_000, 8, 64, method="auto", round_width=8)
    assert plan.elected_by == "analytic"
    assert P.autotune_counters() == {"hits": 0, "misses": 0, "flips": 0}


def test_shape_bucket_quant_model_parity(monkeypatch):
    """Row-count shape buckets (LGBM_TPU_SHAPE_BUCKETS=1): padded rows
    are masked out of every sum, so the quantized model is BYTE-identical
    to the exact-shape run — the invariant that lets the bucket ladder
    collapse the compile count without touching results.  Deterministic
    rounding: the stochastic-rounding uniforms are drawn per PADDED row,
    so that mode legitimately re-randomizes when the pad changes (same
    class of difference as a bagging reseed, not a correctness gap)."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.ops.planner import bucket_rows
    rng = np.random.RandomState(17)
    n = 5000                       # pads to 6144: a real 1.5*2^k rung
    assert bucket_rows(n) == 6144
    X = rng.randn(n, 6)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(float)

    def run():
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        b = lgb.Booster(params={"objective": "binary", "num_leaves": 15,
                                "verbosity": -1,
                                "use_quantized_grad": True,
                                "stochastic_rounding": False},
                        train_set=ds)
        for _ in range(6):
            b.update()
        return b.model_to_string()

    monkeypatch.setenv("LGBM_TPU_SHAPE_BUCKETS", "0")
    exact = run()
    monkeypatch.setenv("LGBM_TPU_SHAPE_BUCKETS", "1")
    bucketed = run()
    assert bucketed == exact
