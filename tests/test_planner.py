"""HBM budget planner (lightgbm_tpu/ops/planner.py).

Planning runs against a FAKE memory model (``budget_bytes`` /
``LGBM_TPU_HBM_BYTES``) so the verdicts are deterministic off-TPU: the
r5 OOM shape must become a planned, feasible run; small shapes must stay
untiled; the int16 psum narrowing decision must match the kernel-side
static bound; and the predicted peak must track reality on a
scaled-down shape (the off-TPU acceptance path).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.ops.planner import (DEFAULT_HBM_BYTES, MIN_TILE_ROWS,
                                      HistPlan, apply_plan,
                                      hbm_limit_bytes, plan_histograms,
                                      predict_peak_bytes)

GB = 1 << 30


def test_small_shape_stays_untiled():
    p = plan_histograms(100_000, 28, 64, num_leaves=63,
                        budget_bytes=16 * GB, accel=True)
    assert p.tile_rows == 0 and p.use_pack
    assert p.feasible and not p.degraded


def test_r5_oom_shape_becomes_planned_run():
    """The exact shape that died in r5 (>=10M x 28, 255 leaves, B=64,
    157.7 GB requested vs ~17 GB HBM): the untiled prediction must land
    in the measured order of magnitude, and the plan must degrade to a
    power-of-two tile whose predicted peak fits a 16 GB budget."""
    p = plan_histograms(11_000_000, 28, 64, num_leaves=255,
                        budget_bytes=16 * GB, accel=True)
    # the unplanned pipeline wildly exceeds HBM (r5 measured 157.7 GB)
    assert p.untiled_peak_bytes > 100 * GB
    assert p.degraded and p.feasible
    assert p.tile_rows >= MIN_TILE_ROWS
    assert p.tile_rows & (p.tile_rows - 1) == 0        # power of two
    assert not p.use_pack     # no whole-dataset record arena when tiled
    assert p.predicted_peak_bytes <= p.budget_bytes
    # 10M flavor of the acceptance shape
    p10 = plan_histograms(10_000_000, 28, 64, num_leaves=255,
                          budget_bytes=16 * GB, accel=True)
    assert p10.feasible


def test_infeasible_verdict():
    p = plan_histograms(11_000_000, 28, 64, num_leaves=255,
                        budget_bytes=256 << 20, accel=True)
    assert not p.feasible
    assert p.tile_rows == MIN_TILE_ROWS    # degraded to the floor


def test_peak_monotone_in_tile():
    for variant in ("scatter", "sorted", "matmul"):
        peaks = [predict_peak_bytes(4_000_000, 28, 64, num_leaves=255,
                                    variant=variant, tile_rows=t,
                                    use_pack=(t == 0), accel=True)[0]
                 for t in (0, 1 << 21, 1 << 18, 1 << 16)]
        assert peaks == sorted(peaks, reverse=True), (variant, peaks)


def test_narrowing_decision_matches_kernel_bound():
    from lightgbm_tpu.ops.histogram import quant_psum_narrow
    for rows, bins in ((1_000, 4), (200_000, 4), (1_000_000, 64)):
        p = plan_histograms(rows, 28, 64, quant=True, quant_bins=bins,
                            budget_bytes=16 * GB)
        assert p.narrow_int16 == quant_psum_narrow(rows, bins)


def test_env_overrides(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_TILE_ROWS", "262144")
    p = plan_histograms(11_000_000, 28, 64, num_leaves=255,
                        budget_bytes=16 * GB, accel=True)
    assert p.tile_rows == 262144 and not p.use_pack and not p.degraded
    monkeypatch.setenv("LGBM_TPU_TILE_ROWS", "off")
    p = plan_histograms(11_000_000, 28, 64, num_leaves=255,
                        budget_bytes=16 * GB, accel=True)
    assert p.tile_rows == 0
    monkeypatch.delenv("LGBM_TPU_TILE_ROWS")
    monkeypatch.setenv("LGBM_TPU_HBM_BYTES", str(8 * GB))
    limit, source = hbm_limit_bytes()
    assert limit == 8 * GB and source == "env"


def test_limit_fallback_has_source():
    limit, source = hbm_limit_bytes()
    assert limit > 0 and source in ("memory_stats", "env", "default")
    if source == "default":
        assert limit == DEFAULT_HBM_BYTES


def test_apply_plan_threads_config(monkeypatch):
    from lightgbm_tpu.grower import GrowerConfig
    monkeypatch.setenv("LGBM_TPU_HBM_BYTES", str(16 * GB))
    cfg, plan = apply_plan(GrowerConfig(num_leaves=63, num_bins=64),
                           100_000, 28)
    assert isinstance(plan, HistPlan)
    assert cfg.tile_rows == plan.tile_rows
    # a tiny fake budget forces tiling + clears the record-arena hoist
    monkeypatch.setenv("LGBM_TPU_HBM_BYTES", str(64 << 20))
    cfg, plan = apply_plan(
        GrowerConfig(num_leaves=255, num_bins=64), 4_000_000, 28,
        accel=True)
    assert plan.degraded and cfg.tile_rows > 0 and not cfg.hist_pack


def test_summary_is_json_ready():
    import json
    p = plan_histograms(1_000_000, 28, 64, budget_bytes=16 * GB)
    d = json.loads(json.dumps(p.summary()))
    assert d["hbm_limit_bytes"] == p.limit_bytes
    assert set(d) >= {"tile_rows", "feasible", "predicted_peak_bytes",
                      "untiled_peak_bytes", "degraded", "variant"}


def test_prediction_tracks_measured_lower_bound():
    """Off-TPU acceptance path: on a scaled-down shape the predicted
    peak must be at least the bytes of the arrays the pipeline REALLY
    allocates (binned matrix + hist cache + update buffer) and within a
    small factor of that floor — i.e. the model is anchored to reality,
    not a fudge constant."""
    rows, F, B, L = 200_000, 28, 64, 255
    floor = (rows * F                  # binned u8
             + L * 3 * F * B * 4       # hist cache f32
             + rows * F * 3 * 4)       # untiled scatter updates
    pred = predict_peak_bytes(rows, F, B, num_leaves=L, variant="scatter",
                              tile_rows=0, accel=False)[0]
    assert floor <= pred <= 12 * floor
    # tiled: the update buffer leaves the model, the residents remain
    pred_t = predict_peak_bytes(rows, F, B, num_leaves=L,
                                variant="scatter", tile_rows=1 << 16,
                                use_pack=False, accel=False)[0]
    floor_t = rows * F + L * 3 * F * B * 4
    assert floor_t <= pred_t < pred


def test_booster_exposes_plan(monkeypatch):
    """The GBDT layer plans at build time and a forced tile flows into
    the grower config (end-to-end threading check)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.randn(600, 6)
    y = (X[:, 0] > 0).astype(float)
    monkeypatch.setenv("LGBM_TPU_TILE_ROWS", "128")
    b = lgb.Booster(params={"objective": "binary", "verbosity": -1,
                            "num_leaves": 7},
                    train_set=lgb.Dataset(X, label=y, free_raw_data=False))
    plan = b.boosting.hist_plan
    assert plan.tile_rows == 128
    assert b.boosting.grower_cfg.tile_rows == 128
    assert not b.boosting.grower_cfg.hist_pack
    b.update()
    assert b.boosting.iter == 1
