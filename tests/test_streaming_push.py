"""Streaming row push (reference: LGBM_DatasetCreateFromSampledColumn +
LGBM_DatasetPushRows, include/LightGBM/c_api.h:98-144)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.dataset import Dataset


def _xy(n=4000, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    X[rng.rand(n, f) < 0.1] = np.nan          # exercise missing bins
    y = (np.nan_to_num(X[:, 0]) + np.nan_to_num(X[:, 1])
         > 1.0).astype(np.float32)
    return X, y


def test_push_rows_matches_bulk_construct():
    X, y = _xy()
    n = len(X)
    bulk = Dataset(X, label=y).construct()
    ds = Dataset.from_sample(X[:1000], n)
    for lo in range(0, n, 700):               # uneven chunks
        ds.push_rows(X[lo:lo + 700])
    assert ds.constructed
    ds.set_label(y)
    # same mappers (same sample prefix is NOT guaranteed — bulk samples
    # from all rows) -> compare by re-binning equivalence instead:
    # bin the same rows through both layouts and check per-feature bins
    for j, f in enumerate(ds.used_features):
        b1 = ds.bin_mappers[f].value_to_bin(np.nan_to_num(X[:50, f]))
        assert b1.max() < ds.bin_mappers[f].num_bin


def test_push_rows_trains_end_to_end():
    X, y = _xy()
    n = len(X)
    ds = Dataset.from_sample(X[:1500], n)
    ds.push_rows(X[:2500])
    ds.push_rows(X[2500:])
    ds.set_label(y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=5)
    pred = bst.predict(X[:100])
    assert pred.shape == (100,)
    # sanity: learned signal (AUC >> 0.5)
    full = bst.predict(X)
    order = np.argsort(full)
    ranks = np.empty(n); ranks[order] = np.arange(1, n + 1)
    npos = y.sum()
    auc = (ranks[y > 0].sum() - npos * (npos + 1) / 2) / (npos * (n - npos))
    assert auc > 0.8, auc


def test_push_rows_identical_when_sample_matches():
    """With the sample equal to the full data, streaming and bulk binning
    must produce the IDENTICAL binned matrix."""
    X, y = _xy(n=1500)
    bulk = Dataset(X, label=y,
                   params={"bin_construct_sample_cnt": 10 ** 9}).construct()
    ds = Dataset.from_sample(X, len(X))
    ds.push_rows(X[:800])
    ds.push_rows(X[800:])
    np.testing.assert_array_equal(ds.binned, bulk.binned)
    assert ds.used_features == bulk.used_features


def test_push_rows_guards():
    X, y = _xy(n=100)
    ds = Dataset.from_sample(X, 100)
    with pytest.raises(ValueError, match="push past the end"):
        ds.push_rows(np.random.rand(200, X.shape[1]))
    ds.push_rows(X)
    with pytest.raises(RuntimeError, match="already finished"):
        ds.push_rows(X[:1])
    with pytest.raises(RuntimeError, match="from_sample"):
        Dataset(X, label=y).push_rows(X[:1])


def test_push_rows_sparse_chunks():
    sps = pytest.importorskip("scipy.sparse")
    rng = np.random.RandomState(0)
    n, f = 2000, 20
    Xs = sps.random(n, f, density=0.1, random_state=0, format="csr")
    Xd = Xs.toarray()
    y = (np.asarray(Xs.sum(axis=1)).ravel() > 0.5).astype(np.float32)
    ds = Dataset.from_sample(Xd[:500], n,
                         params={"min_data_in_leaf": 5})
    ds.push_rows(Xs[:1200])                    # sparse chunk
    ds.push_rows(Xd[1200:])                    # dense chunk
    ds.set_label(y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    ds, num_boost_round=3)
    assert bst.predict(Xd[:10]).shape == (10,)
