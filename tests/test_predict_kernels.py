"""Traversal-kernel parity matrix + predict planner election.

The three traversal programs (while / fori / fused,
ops/predict_kernels.py) share ONE decision-step expression, so their
leaf indices must be BIT-identical across every precision, missing
type, categorical bitset, multiclass layout and ragged last tile — the
invariant the whole inference-kernel election rests on.  The serving
epilogue probe (device f32 leaf sum vs host f64 gather) promotes and
demotes per forest; both directions keep ``predict_raw_padded``
bit-equal to the host path.
"""

import copy
import json

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.fleet.lowprec import quantize_forest
from lightgbm_tpu.ops import planner as P
from lightgbm_tpu.predict import DeviceForest, gather_leaf_sum

VARIANTS = ("while", "fori", "fused")
# not a multiple of any fused tile rung -> the last tile is ragged
EVAL_ROWS = 700
TILE = 128


def _train(X, y, num_class=1, categorical=None, rounds=8, leaves=7,
           **extra):
    params = {"objective": "binary", "verbosity": -1, "num_leaves": leaves,
              "min_data_in_leaf": 5}
    if num_class > 1:
        params.update(objective="multiclass", num_class=num_class)
    params.update(extra)
    ds = lgb.Dataset(X, label=y, categorical_feature=categorical or "auto")
    return lgb.train(params, ds, num_boost_round=rounds, verbose_eval=False)


def _forest(bst):
    return bst._forest(0, len(bst.models) // bst.num_tree_per_iteration)


def _salted(X):
    """Eval batch with the routing edge cases planted in known rows."""
    Xs = np.array(X[:EVAL_ROWS], np.float64)
    Xs[0, :] = 0.0
    Xs[1, :] = np.nan
    Xs[2, :] = -1e30
    Xs[3, :] = 1e30
    return Xs


@pytest.fixture(scope="module")
def models():
    """One booster per routing regime: categorical + NaN-missing,
    zero-as-missing, no-missing, multiclass."""
    rng = np.random.RandomState(7)
    n = 1500
    out = {}

    cat = rng.randint(0, 12, n).astype(np.float64)
    dense = rng.randn(n)
    dense[rng.rand(n) < 0.2] = np.nan
    X = np.column_stack([cat, dense, rng.randn(n)])
    y = (np.isin(cat, [1, 4, 9]) | (np.nan_to_num(dense) > 0.7)
         ).astype(float)
    out["cat_nan"] = (_train(X, y, categorical=[0]), X)

    Xz = rng.randn(n, 4)
    Xz[rng.rand(n, 4) < 0.3] = 0.0
    yz = (Xz[:, 0] + Xz[:, 2] > 0).astype(float)
    out["zero_missing"] = (_train(Xz, yz, zero_as_missing=True), Xz)

    Xc = rng.rand(n, 4) + 0.5          # strictly positive, nothing missing
    yc = (Xc[:, 0] * Xc[:, 1] > Xc[:, 2]).astype(float)
    out["none_missing"] = (_train(Xc, yc), Xc)

    Xm = rng.randn(n, 5)
    ym = rng.randint(0, 3, n).astype(float)
    out["multiclass"] = (_train(Xm, ym, num_class=3, rounds=5), Xm)
    return out


def _leaf_matrix(forest, Xs, precision):
    """Leaf indices per variant at one precision; dict variant->array."""
    f = quantize_forest(forest, precision) if precision != "f32" else forest
    import jax.numpy as jnp
    X32 = jnp.asarray(np.asarray(Xs, np.float32))
    out = {}
    for v in VARIANTS:
        dev = DeviceForest(f, precision=precision, variant=v,
                           tile_rows=TILE)
        out[v] = np.asarray(dev._leaves_jit(X32))
    return out


@pytest.mark.parametrize("precision", ["f32", "bf16", "int8"])
@pytest.mark.parametrize(
    "case", ["cat_nan", "zero_missing", "none_missing", "multiclass"])
def test_variant_parity_matrix(models, case, precision):
    bst, X = models[case]
    leaves = _leaf_matrix(_forest(bst), _salted(X), precision)
    for v in ("fori", "fused"):
        assert np.array_equal(leaves["while"], leaves[v]), (
            f"{case}/{precision}: {v} leaf indices diverge from while")


def test_fused_ragged_last_tile(models):
    """Rows that do not divide the tile exercise the pad-and-slice arm:
    every ragged width must match the while baseline bit-for-bit."""
    bst, X = models["cat_nan"]
    forest = _forest(bst)
    import jax.numpy as jnp
    from lightgbm_tpu.ops import predict_kernels as PK
    dev = DeviceForest(forest, variant="while", tile_rows=TILE)
    for rows in (1, TILE - 1, TILE, TILE + 1, 2 * TILE + 17):
        X32 = jnp.asarray(np.asarray(_salted(X)[:rows], np.float32))
        ref = np.asarray(PK.leaves_while(dev, X32))
        got = np.asarray(PK.fused_traverse(dev, X32, TILE))
        assert got.shape == ref.shape == (forest.num_trees, rows)
        assert np.array_equal(ref, got), f"ragged rows={rows} diverged"


def test_serving_parity_all_variants(models):
    """predict_raw_padded (the serving entry point) is bit-equal to
    Booster.predict(raw_score=True) whatever variant routes the rows."""
    bst, X = models["cat_nan"]
    forest = _forest(bst)
    ref = bst.predict(X[:EVAL_ROWS], raw_score=True)
    for v in VARIANTS:
        dev = DeviceForest(forest, variant=v, tile_rows=TILE)
        raw = dev.predict_raw_padded(X[:EVAL_ROWS])[0]
        assert np.array_equal(raw, ref), f"variant {v} broke serving parity"


def test_serving_parity_multiclass(models):
    bst, X = models["multiclass"]
    forest = _forest(bst)
    K = bst.num_tree_per_iteration
    ref = bst.predict(X[:EVAL_ROWS], raw_score=True).T      # [K, n]
    dev = DeviceForest(forest, variant="fori")
    raw = dev.predict_raw_padded(X[:EVAL_ROWS], num_class=K)
    assert np.array_equal(raw, ref)


# ----------------------------------------------------------------------
# epilogue probe: promotion, demotion, env pin
# ----------------------------------------------------------------------


def _with_leaves(forest, leaf_value):
    f = copy.copy(forest)
    f.leaf_value = np.asarray(leaf_value, np.float64)
    return f


def test_epilogue_promotes_integer_leaves(models):
    """Integer-valued leaves sum exactly in f32 -> the device epilogue
    passes the bit-exactness probe and predict_raw_padded's output is
    STILL bit-equal to the host f64 gather."""
    bst, X = models["none_missing"]
    forest = _forest(bst)
    f = _with_leaves(forest, np.round(forest.leaf_value * 50))
    dev = DeviceForest(f, variant="fori")
    assert dev._epilogue_verified(1)
    Xs = np.asarray(X[:333], np.float64)
    raw = dev.predict_raw_padded(Xs)
    import jax.numpy as jnp
    leaves = np.asarray(dev._leaves_jit(jnp.asarray(Xs, jnp.float32)))
    assert np.array_equal(raw, gather_leaf_sum(f, leaves, 1))


def test_epilogue_demotes_on_f32_rounding(models):
    """Leaf values spanning 1e8 vs 1.0 make f32 sums drop the low bits;
    the probe must demote to the host path — and the serving output must
    still be the f64 host gather bit-for-bit."""
    bst, X = models["none_missing"]
    forest = _forest(bst)
    lv = np.ones_like(forest.leaf_value)
    lv[0, :] = 1e8
    f = _with_leaves(forest, lv)
    dev = DeviceForest(f, variant="fori")
    assert not dev._epilogue_verified(1)
    Xs = np.asarray(X[:128], np.float64)
    raw = dev.predict_raw_padded(Xs)
    import jax.numpy as jnp
    leaves = np.asarray(dev._leaves_jit(jnp.asarray(Xs, jnp.float32)))
    assert np.array_equal(raw, gather_leaf_sum(f, leaves, 1))


def test_epilogue_env_pin(models, monkeypatch):
    """LGBM_TPU_PREDICT_EPILOGUE=0 pins the host path even for a forest
    the probe would promote."""
    bst, _ = models["none_missing"]
    forest = _forest(bst)
    f = _with_leaves(forest, np.round(forest.leaf_value * 50))
    monkeypatch.setenv("LGBM_TPU_PREDICT_EPILOGUE", "0")
    dev = DeviceForest(f, variant="fori")
    assert not dev._epilogue_verified(1)


# ----------------------------------------------------------------------
# planner election: env gates, byte models, measured store
# ----------------------------------------------------------------------


def test_kernel_env_override(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_PREDICT_KERNEL", "while")
    plan = P.plan_predict(num_trees=8, nodes_dim=7, leaves_dim=8,
                          features=4, rows=1000)
    assert plan.variant == "while" and plan.elected_by == "env"
    monkeypatch.setenv("LGBM_TPU_PREDICT_KERNEL", "bogus")
    plan = P.plan_predict(num_trees=8, nodes_dim=7, leaves_dim=8,
                          features=4, rows=1000)
    assert plan.elected_by != "env"        # unknown names are ignored


def test_chunk_env_override(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_PREDICT_CHUNK", "4096")
    assert P.elect_predict_chunk(8, 7, 8, 4) == 4096
    assert P.elect_csr_chunk(4) == 4096
    monkeypatch.delenv("LGBM_TPU_PREDICT_CHUNK")
    assert P.elect_predict_chunk(8, 7, 8, 4) >= P.MIN_BUCKET_ROWS


def test_chunk_respects_budget():
    """A starved budget pins the chunk at the ladder floor; a generous
    one climbs it (never past MAX_PREDICT_CHUNK_ROWS)."""
    small = P.elect_predict_chunk(64, 255, 256, 32, budget=1 << 20)
    big = P.elect_predict_chunk(64, 255, 256, 32, budget=1 << 40)
    assert small == P.MIN_BUCKET_ROWS
    assert small <= big <= P.MAX_PREDICT_CHUNK_ROWS


def test_predict_bucket_key_namespace():
    key = P.predict_bucket_key(100_000, 12, 40, 1, "f32")
    assert key.startswith("p-")            # never collides with hist keys
    assert key == P.predict_bucket_key(100_001, 12, 40, 1, "f32")  # rung


def test_measured_predict_election_roundtrip(tmp_path):
    store = str(tmp_path)                    # a store DIRECTORY
    store_file = P._autotune_path(store)
    shape = dict(rows=50_000, features=12, num_trees=40, num_class=1,
                 precision="f32")
    assert P.measured_predict_election(path=store, **shape) is None
    P.record_predict_timing(variant="fori", seconds=0.5, path=store, **shape)
    P.record_predict_timing(variant="fused", seconds=0.2, path=store, **shape)
    P.record_predict_timing(variant="while", seconds=1.5, path=store, **shape)
    best = P.measured_predict_election(path=store, **shape)
    assert best["variant"] == "fused"
    # a future store's unknown variant name is skipped, not adopted
    with open(store_file) as fh:
        d = json.load(fh)
    d["entries"][best["key"]]["warp9"] = {"seconds": 0.01}
    with open(store_file, "w") as fh:
        json.dump(d, fh)
    assert P.measured_predict_election(path=store, **shape)["variant"] == \
        "fused"


def test_fused_tile_ladder_fits_or_none():
    got = P.plan_predict_fused_tile(8, 7, 4, vmem_bytes=1 << 30)
    assert got is not None and got["tile_rows"] == P.FUSED_PREDICT_TILES[0]
    assert P.plan_predict_fused_tile(4000, 2047, 256, vmem_bytes=1 << 16) \
        is None


def test_deviceforest_chunk_shrinks_to_batch(models):
    """Small batches never pad out to the elected chunk ceiling."""
    bst, _ = models["none_missing"]
    dev = DeviceForest(_forest(bst), variant="fori")
    assert dev._call_chunk(10) <= P.bucket_rows(10)
    assert dev._call_chunk(10 ** 9) == dev.chunk_rows
