"""Fault-tolerant training: checkpoint bundles, bit-identical resume,
corruption fallback, atomic model writes (docs/RESILIENCE.md).

The core contract under test is the acceptance bar of PR 2: a run killed
after a checkpoint at iteration k and resumed via ``resume_from``
produces a model file BYTE-identical to the uninterrupted run — across
bagging, GOSS and DART configs — and a corrupted newest bundle is
detected (sha256 manifest) and skipped for the previous good one.
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.dataset import Dataset
from lightgbm_tpu.resilience import (CheckpointCorruptError,
                                     CheckpointManager,
                                     CheckpointNotFoundError,
                                     load_checkpoint, save_checkpoint)


def _data(seed=0, n=400, f=6):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0.8).astype(np.float32)
    Xv = rng.rand(n // 2, f)
    yv = (Xv[:, 0] + Xv[:, 1] * Xv[:, 2] > 0.8).astype(np.float32)
    return X, y, Xv, yv


BASE = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
        "min_data_in_leaf": 5}


def _resume_parity(tmp_path, params, rounds=12, die_after=7, freq=3,
                   early_stopping_rounds=None):
    """Train full; train-and-die at ``die_after`` with bundles every
    ``freq``; resume from the bundle dir; compare final model bytes."""
    X, y, Xv, yv = _data()
    kw = dict(verbose_eval=False,
              early_stopping_rounds=early_stopping_rounds)

    er_full = {}
    full = lgb.train(params, Dataset(X, label=y), rounds,
                     valid_sets=[Dataset(Xv, label=yv)],
                     evals_result=er_full, **kw)
    full.save_model(str(tmp_path / "full.txt"))

    er_part = {}
    lgb.train(params, Dataset(X, label=y), die_after,
              valid_sets=[Dataset(Xv, label=yv)], evals_result=er_part,
              snapshot_freq=freq, snapshot_out=str(tmp_path / "part.txt"),
              **kw)

    er_res = {}
    res = lgb.train(params, Dataset(X, label=y), rounds,
                    valid_sets=[Dataset(Xv, label=yv)], evals_result=er_res,
                    resume_from=str(tmp_path / "part.txt.ckpt"), **kw)
    res.save_model(str(tmp_path / "res.txt"))

    a = (tmp_path / "full.txt").read_bytes()
    b = (tmp_path / "res.txt").read_bytes()
    assert a == b, "resumed model file is not byte-identical"
    assert full.best_iteration == res.best_iteration
    assert er_full == er_res, "resumed eval history diverged"
    return full, res


def test_resume_bit_identical_bagging(tmp_path):
    _resume_parity(tmp_path, {**BASE, "bagging_fraction": 0.7,
                              "bagging_freq": 2, "feature_fraction": 0.8})


def test_resume_bit_identical_dart(tmp_path):
    _resume_parity(tmp_path, {**BASE, "boosting": "dart", "drop_rate": 0.5})


def test_resume_bit_identical_dart_nonuniform(tmp_path):
    _resume_parity(tmp_path, {**BASE, "boosting": "dart", "drop_rate": 0.5,
                              "uniform_drop": False})


def test_resume_bit_identical_goss(tmp_path):
    _resume_parity(tmp_path, {**BASE, "boosting": "goss",
                              "learning_rate": 0.3})


def test_resume_bit_identical_rf(tmp_path):
    _resume_parity(tmp_path, {**BASE, "boosting": "rf",
                              "bagging_fraction": 0.7, "bagging_freq": 1})


def test_resume_bit_identical_cegb(tmp_path):
    """CEGB carries cross-iteration device state (used features + lazy
    row coverage); already-charged penalties must not re-charge after
    resume."""
    _resume_parity(tmp_path, {
        **BASE, "cegb_tradeoff": 0.5, "cegb_penalty_split": 0.1,
        "cegb_penalty_feature_coupled": [0.4] * 6,
        "cegb_penalty_feature_lazy": [0.3] * 6})


def test_resume_early_stopping_state(tmp_path):
    """The patience window carries across the kill: the resumed run must
    stop at the same iteration with the same best score."""
    rng = np.random.RandomState(3)
    X = rng.rand(400, 6)
    y = (X[:, 0] > 0.5).astype(np.float32)
    Xv = rng.rand(150, 6)
    yv = (rng.rand(150) > 0.5).astype(np.float32)   # noise: stops early
    kw = dict(verbose_eval=False, early_stopping_rounds=4)

    full = lgb.train(BASE, Dataset(X, label=y), 40,
                     valid_sets=[Dataset(Xv, label=yv)], **kw)
    full.save_model(str(tmp_path / "full.txt"))
    assert full.best_iteration < 40, "test needs early stopping to fire"

    lgb.train(BASE, Dataset(X, label=y), 4,
              valid_sets=[Dataset(Xv, label=yv)],
              snapshot_freq=2, snapshot_out=str(tmp_path / "p.txt"), **kw)
    res = lgb.train(BASE, Dataset(X, label=y), 40,
                    valid_sets=[Dataset(Xv, label=yv)],
                    resume_from=str(tmp_path / "p.txt.ckpt"), **kw)
    res.save_model(str(tmp_path / "res.txt"))
    assert (tmp_path / "full.txt").read_bytes() == \
        (tmp_path / "res.txt").read_bytes()
    assert res.best_iteration == full.best_iteration
    assert res.best_score == full.best_score


def test_corrupted_newest_bundle_falls_back(tmp_path):
    """Bit-flip the newest bundle: it must be detected and skipped, and
    resume must continue from the previous verified one."""
    X, y, _, _ = _data()
    lgb.train(BASE, Dataset(X, label=y), 9, verbose_eval=False,
              snapshot_freq=3, snapshot_out=str(tmp_path / "m.txt"))
    d = tmp_path / "m.txt.ckpt"
    bundles = sorted(p for p in os.listdir(d) if p.endswith(".lgbckpt"))
    assert bundles == ["ckpt_iter_00000003.lgbckpt",
                       "ckpt_iter_00000006.lgbckpt",
                       "ckpt_iter_00000009.lgbckpt"]
    newest = d / bundles[-1]
    blob = bytearray(newest.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    newest.write_bytes(bytes(blob))

    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(str(newest))
    ck = CheckpointManager(str(d)).latest_verified()
    assert ck.iteration == 6

    res = lgb.train(BASE, Dataset(X, label=y), 9, verbose_eval=False,
                    resume_from=str(d))
    assert len(res.boosting.models) == 9


def test_truncated_bundle_detected(tmp_path):
    X, y, _, _ = _data()
    bst = lgb.train(BASE, Dataset(X, label=y), 3, verbose_eval=False)
    p = str(tmp_path / "one.lgbckpt")
    save_checkpoint(bst, p, iteration=3)
    blob = (tmp_path / "one.lgbckpt").read_bytes()
    (tmp_path / "one.lgbckpt").write_bytes(blob[:len(blob) // 2])
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(p)


def test_all_bundles_corrupt_raises_not_found(tmp_path):
    X, y, _, _ = _data()
    lgb.train(BASE, Dataset(X, label=y), 4, verbose_eval=False,
              snapshot_freq=2, snapshot_out=str(tmp_path / "m.txt"))
    d = tmp_path / "m.txt.ckpt"
    for name in os.listdir(d):
        if name.endswith(".lgbckpt"):
            (d / name).write_bytes(b"garbage")
    with pytest.raises(CheckpointNotFoundError):
        CheckpointManager(str(d)).latest_verified()


def test_retention_keeps_last_k(tmp_path):
    X, y, _, _ = _data()
    lgb.train(BASE, Dataset(X, label=y), 10, verbose_eval=False,
              snapshot_freq=2, snapshot_out=str(tmp_path / "m.txt"),
              snapshot_keep=2)
    d = tmp_path / "m.txt.ckpt"
    bundles = sorted(p for p in os.listdir(d) if p.endswith(".lgbckpt"))
    assert bundles == ["ckpt_iter_00000008.lgbckpt",
                       "ckpt_iter_00000010.lgbckpt"]


def test_resume_from_specific_bundle_file(tmp_path):
    X, y, _, _ = _data()
    lgb.train(BASE, Dataset(X, label=y), 6, verbose_eval=False,
              snapshot_freq=2, snapshot_out=str(tmp_path / "m.txt"))
    bundle = tmp_path / "m.txt.ckpt" / "ckpt_iter_00000004.lgbckpt"
    res = lgb.train(BASE, Dataset(X, label=y), 6, verbose_eval=False,
                    resume_from=str(bundle))
    assert len(res.boosting.models) == 6


def test_resume_missing_location_raises(tmp_path):
    X, y, _, _ = _data()
    with pytest.raises(CheckpointNotFoundError):
        lgb.train(BASE, Dataset(X, label=y), 3, verbose_eval=False,
                  resume_from=str(tmp_path / "nope"))


def test_bundle_model_txt_member_loads_standalone(tmp_path):
    """The model.txt member is a complete reference-format model."""
    X, y, _, _ = _data()
    bst = lgb.train(BASE, Dataset(X, label=y), 5, verbose_eval=False)
    p = str(tmp_path / "b.lgbckpt")
    save_checkpoint(bst, p, iteration=5)
    ck = load_checkpoint(p)
    loaded = lgb.Booster(model_str=ck.model_str)
    np.testing.assert_allclose(loaded.predict(X[:16]), bst.predict(X[:16]),
                               rtol=1e-6)


def test_save_model_atomic_creates_parent_dirs(tmp_path):
    """Satellite: snapshot_out / save_model into a nonexistent directory
    must work, and no temp sibling may linger."""
    X, y, _, _ = _data()
    bst = lgb.train(BASE, Dataset(X, label=y), 2, verbose_eval=False)
    target = tmp_path / "does" / "not" / "exist" / "model.txt"
    bst.save_model(str(target))
    assert target.is_file()
    siblings = os.listdir(target.parent)
    assert siblings == ["model.txt"], siblings
    reload = lgb.Booster(model_file=str(target))
    np.testing.assert_allclose(reload.predict(X[:8]), bst.predict(X[:8]),
                               rtol=1e-6)


def test_snapshot_out_into_new_dir(tmp_path):
    X, y, _, _ = _data()
    out = tmp_path / "fresh" / "dir" / "m.txt"
    lgb.train(BASE, Dataset(X, label=y), 4, verbose_eval=False,
              snapshot_freq=2, snapshot_out=str(out))
    assert (out.parent / "m.txt.ckpt" / "index.json").is_file()
