"""Histogram kernel tests: matmul vs scatter vs brute-force NumPy."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import (build_histogram, histogram_matmul,
                                        histogram_scatter)


def brute_force(binned, grad, hess, mask, B):
    """[3, F, B] reference histogram from a row-major host matrix."""
    n, F = binned.shape
    out = np.zeros((3, F, B), np.float64)
    for i in range(n):
        for f in range(F):
            b = binned[i, f]
            out[0, f, b] += grad[i] * mask[i]
            out[1, f, b] += hess[i] * mask[i]
            out[2, f, b] += mask[i]
    return out


@pytest.mark.parametrize("method", ["scatter", "matmul", "matmul_f32",
                                    "pallas"])
def test_histogram_matches_brute_force(method):
    rng = np.random.RandomState(0)
    n, F, B = 500, 7, 16
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = rng.rand(n).astype(np.float32)
    mask = (rng.rand(n) < 0.7).astype(np.float32)
    expect = brute_force(binned, grad, hess, mask, B)
    got = np.asarray(build_histogram(jnp.asarray(binned.T), jnp.asarray(grad),
                                     jnp.asarray(hess), jnp.asarray(mask),
                                     B, method=method))
    np.testing.assert_allclose(got, expect, rtol=2e-2, atol=2e-2)


def test_histogram_scatter_exact():
    rng = np.random.RandomState(1)
    n, F, B = 300, 4, 8
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    mask = np.ones(n, np.float32)
    expect = brute_force(binned, grad, hess, mask, B)
    got = np.asarray(build_histogram(jnp.asarray(binned.T), jnp.asarray(grad),
                                     jnp.asarray(hess), jnp.asarray(mask),
                                     B, method="scatter"))
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)


def test_matmul_block_boundary():
    # n not a multiple of the block size must still be correct
    rng = np.random.RandomState(2)
    n, F, B = 100, 3, 4
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    mask = np.ones(n, np.float32)
    a = np.asarray(build_histogram(jnp.asarray(binned.T), jnp.asarray(grad),
                                   jnp.asarray(hess), jnp.asarray(mask),
                                   B, method="matmul", ))
    b = np.asarray(build_histogram(jnp.asarray(binned.T), jnp.asarray(grad),
                                   jnp.asarray(hess), jnp.asarray(mask),
                                   B, method="scatter"))
    np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-2)


def test_subtraction_trick():
    rng = np.random.RandomState(3)
    n, F, B = 400, 5, 16
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    left = (rng.rand(n) < 0.5).astype(np.float32)
    full = build_histogram(jnp.asarray(binned.T), jnp.asarray(grad),
                           jnp.asarray(hess), jnp.ones(n, jnp.float32), B,
                           method="scatter")
    hl = build_histogram(jnp.asarray(binned.T), jnp.asarray(grad),
                         jnp.asarray(hess), jnp.asarray(left), B,
                         method="scatter")
    hr = np.asarray(full) - np.asarray(hl)
    expect = brute_force(binned, grad, hess, 1.0 - left, B)
    np.testing.assert_allclose(hr, expect, rtol=1e-4, atol=1e-4)


def test_compacted_histogram_matches_masked():
    """Bucketed compaction must be numerically identical to the full
    masked pass (ops/histogram.py compacted_histogram)."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import (build_histogram,
                                            capacity_schedule,
                                            compacted_histogram)
    rng = np.random.RandomState(42)
    n, F, B = 10_000, 6, 16
    binned = jnp.asarray(rng.randint(0, B, size=(F, n)).astype(np.uint8))
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    hess = jnp.asarray(rng.rand(n).astype(np.float32))
    weights = jnp.asarray((rng.rand(n) < 0.8).astype(np.float32) * 1.5)
    caps = capacity_schedule(n, min_cap=256)
    assert len(caps) > 3
    for frac in (0.001, 0.3, 0.9):   # exercises several capacity buckets
        member = jnp.asarray(rng.rand(n) < frac)
        full = build_histogram(binned, grad, hess,
                               weights * member, B, method="scatter")
        comp = compacted_histogram(binned, grad, hess, weights, member, B,
                                   caps, method="scatter")
        np.testing.assert_allclose(np.asarray(comp), np.asarray(full),
                                   rtol=1e-5, atol=1e-5)


def test_pallas_matches_scatter_uneven_shapes():
    """Pallas VPU kernel padding paths: rows not a multiple of the block,
    features not a multiple of the tile, odd bin count."""
    rng = np.random.RandomState(3)
    n, F, B = 2579, 5, 17
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = rng.rand(n).astype(np.float32)
    mask = (rng.rand(n) < 0.6).astype(np.float32)
    ref = np.asarray(build_histogram(jnp.asarray(binned.T), jnp.asarray(grad),
                                     jnp.asarray(hess), jnp.asarray(mask),
                                     B, method="scatter"))
    got = np.asarray(build_histogram(jnp.asarray(binned.T), jnp.asarray(grad),
                                     jnp.asarray(hess), jnp.asarray(mask),
                                     B, method="pallas"))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_measured_best_method_cpu_short_circuits():
    """On CPU the timed probe must not run (scatter is the measured winner
    every round); on accelerators it times the variants and caches."""
    from lightgbm_tpu.ops.histogram import measured_best_method
    assert measured_best_method(10_000, 8, 64) == "scatter"


def test_segment_histogram_sorted_matches_scatter():
    """The TPU sorted-arena segment histogram must agree with the scatter
    formulation for arbitrary slot assignments, weights, and ladders."""
    from lightgbm_tpu.ops.histogram import (capacity_schedule,
                                            segment_histogram,
                                            segment_histogram_sorted)
    rng = np.random.RandomState(11)
    for n, F, S, B in [(10_000, 28, 128, 64), (5_000, 7, 16, 32),
                       (777, 3, 4, 8), (1000, 5, 1, 8),
                       (3_000, 4, 8, 300)]:   # u16 bins: no packing
        dt = np.uint8 if B <= 256 else np.uint16
        binned = jnp.asarray(rng.randint(0, B - 1, (F, n)).astype(dt))
        g = jnp.asarray(rng.randn(n).astype(np.float32))
        h = jnp.abs(g) + 0.1
        w = jnp.asarray((rng.rand(n) > 0.3).astype(np.float32) * 1.5)
        slot = jnp.asarray(rng.randint(0, S + 1, n).astype(np.int32))
        ref = np.asarray(segment_histogram(binned, g, h, w, slot, S, B))
        from lightgbm_tpu.ops.histogram import pack_cols_u32
        packed = pack_cols_u32(binned, g, h, w)
        for caps in (None, capacity_schedule(n, min_cap=512)):
            for pk in (None, packed):   # fused u32 record path too
                got = np.asarray(segment_histogram_sorted(
                    binned, g, h, w, slot, S, B, f32_vals=True, caps=caps,
                    packed=pk))
                np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_segment_histogram_sorted_all_dropped():
    from lightgbm_tpu.ops.histogram import segment_histogram_sorted
    rng = np.random.RandomState(1)
    n = 1000
    binned = jnp.asarray(rng.randint(0, 7, (5, n)).astype(np.uint8))
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    out = segment_histogram_sorted(binned, g, g + 2.0, jnp.ones(n), 
                                   jnp.full(n, 4, jnp.int32), 4, 8,
                                   f32_vals=True)
    assert float(jnp.abs(out).sum()) == 0.0


def test_segment_histogram_small_round_path(monkeypatch):
    """The slot-expanded one-pass branch (num_live <= 42 on the sorted
    dispatch) must agree with the arena path and the scatter reference,
    on both sides of the dispatch boundary."""
    import jax.numpy as jnp_
    from lightgbm_tpu.ops.histogram import (capacity_schedule,
                                            compacted_segment_histogram,
                                            segment_histogram)
    monkeypatch.setenv("LGBM_TPU_SEGHIST", "sorted")
    rng = np.random.RandomState(5)
    n, F, S, B = 6_000, 9, 64, 32
    binned = jnp.asarray(rng.randint(0, B - 1, (F, n)).astype(np.uint8))
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    h = jnp.abs(g) + 0.1
    w = jnp.asarray((rng.rand(n) > 0.2).astype(np.float32))
    caps = capacity_schedule(n, min_cap=512)
    for live in (1, 3, 4, 5, 17, 42, 43, 60):
        # slots >= live are dropped lanes (as the grower produces)
        slot = jnp.asarray(
            np.where(rng.rand(n) < 0.7, rng.randint(0, live, n), S)
            .astype(np.int32))
        ref = np.asarray(segment_histogram(binned, g, h, w, slot, S, B))
        got = np.asarray(compacted_segment_histogram(
            binned, g, h, w, slot, S, B, caps, f32_vals=True,
            num_live=jnp_.int32(live)))
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4,
                                   err_msg=f"live={live}")


def test_take_from_table_exact(monkeypatch):
    """One-hot matmul table lookup must be bit-exact vs a plain gather
    ([n]-from-small-table gathers serialize on the TPU backend; the
    matmul form replaces them on hot paths — score update, prediction)."""
    from lightgbm_tpu.ops import histogram as H
    monkeypatch.setattr(H, "on_accelerator", lambda: True)
    rng = np.random.RandomState(0)
    table = (rng.randn(255) * 1e3).astype(np.float32)
    idx = rng.randint(0, 255, size=10_001).astype(np.int32)
    out = np.asarray(H.take_from_table(jnp.asarray(table), jnp.asarray(idx)))
    assert np.array_equal(out, table[idx])
    t2 = rng.randn(255, 7).astype(np.float32)
    out2 = np.asarray(H.take_from_table(jnp.asarray(t2), jnp.asarray(idx)))
    assert np.array_equal(out2, t2[idx])
    # integer tables fall back to the gather (bf16 cast would be lossy)
    t3 = rng.randint(0, 1 << 20, 255).astype(np.int32)
    out3 = np.asarray(H.take_from_table(jnp.asarray(t3), jnp.asarray(idx)))
    assert np.array_equal(out3, t3[idx])


# ---------------------------------------------------------------- tiling
# Row-tiled execution (ops/planner.py HBM budget planner): every kernel
# streams row tiles through a scan/fori accumulator in PINNED tile-major
# order, so tiled results equal untiled ones BIT-FOR-BIT — the integer
# family by int32 associativity, the f32 scatter/sorted kernels by
# identical per-bin/per-block add order.  Tile sizes cover a ragged last
# tile, a tiny tile, and tile_rows > n (degenerates to untiled).

_TILE_SIZES = [128, 192, 7, 4096]   # ragged / odd / tiny / > n


def _tile_data(seed=3, n=1000, F=7, S=16, B=32):
    rng = np.random.RandomState(seed)
    binned = jnp.asarray(rng.randint(0, B - 1, (F, n)).astype(np.uint8))
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    h = jnp.abs(g) + 0.1
    w = jnp.asarray((rng.rand(n) > 0.3).astype(np.float32) * 1.5)
    slot = jnp.asarray(rng.randint(0, S + 1, n).astype(np.int32))
    return binned, g, h, w, slot, n, F, S, B


@pytest.mark.parametrize("tile", _TILE_SIZES)
def test_tiled_scatter_bit_parity(tile):
    from lightgbm_tpu.ops.histogram import _vals_t, segment_histogram
    binned, g, h, w, slot, n, F, S, B = _tile_data()
    vals = _vals_t(g, h, w)
    a = np.asarray(histogram_scatter(binned, vals, B))
    b = np.asarray(histogram_scatter(binned, vals, B, tile_rows=tile))
    assert np.array_equal(a, b)
    a = np.asarray(segment_histogram(binned, g, h, w, slot, S, B))
    b = np.asarray(segment_histogram(binned, g, h, w, slot, S, B,
                                     tile_rows=tile))
    assert np.array_equal(a, b)


@pytest.mark.parametrize("tile", _TILE_SIZES)
def test_tiled_sorted_bit_parity(tile):
    """Sorted-arena f32 kernel: hoisted whole-arena gathers (untiled) vs
    per-block in-loop record assembly (tiled) — same blocks, same pinned
    fold order, bit-identical; with and without the fused u32 records."""
    from lightgbm_tpu.ops.histogram import (pack_cols_u32,
                                            segment_histogram,
                                            segment_histogram_sorted)
    binned, g, h, w, slot, n, F, S, B = _tile_data()
    for pk in (None, pack_cols_u32(binned, g, h, w)):
        a = np.asarray(segment_histogram_sorted(
            binned, g, h, w, slot, S, B, f32_vals=True, packed=pk))
        b = np.asarray(segment_histogram_sorted(
            binned, g, h, w, slot, S, B, f32_vals=True, packed=pk,
            tile_rows=tile))
        assert np.array_equal(a, b), f"pk={pk is not None}"
    # and the sorted result still matches the scatter reference
    ref = np.asarray(segment_histogram(binned, g, h, w, slot, S, B))
    np.testing.assert_allclose(b, ref, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("tile", _TILE_SIZES)
def test_tiled_matmul_parity(tile):
    """Matmul family: any tile >= the streaming block leaves the block
    partition unchanged (bit-identical); a smaller tile refines it —
    deterministic, within f32 reassociation of the same sums."""
    from lightgbm_tpu.ops.histogram import _vals_t, histogram_matmul
    binned, g, h, w, slot, n, F, S, B = _tile_data()
    vals = _vals_t(g, h, w)
    block = 64
    a = np.asarray(histogram_matmul(binned, vals, B, block_rows=block))
    b = np.asarray(histogram_matmul(binned, vals, B, block_rows=block,
                                    tile_rows=tile))
    if tile >= block:
        assert np.array_equal(a, b)
    else:
        np.testing.assert_allclose(a, b, rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize("tile", _TILE_SIZES)
def test_tiled_int_family_exact(tile):
    """Quantized integer kernels are exactly associative: EVERY tile
    size equals untiled bit-for-bit, across the whole family."""
    import jax

    from lightgbm_tpu.ops import histogram as H
    binned, g, h, w, slot, n, F, S, B = _tile_data()
    gq, hq, _, _ = H.quantize_gradients(g, h, w, 8, jax.random.PRNGKey(0))
    member = w > 0
    lv = H.quant_levels(8)
    vals = H._vals_t_int(gq, hq, member)
    pairs = [
        (H.histogram_scatter_int(binned, vals, B, levels=lv),
         H.histogram_scatter_int(binned, vals, B, levels=lv,
                                 tile_rows=tile)),
        (H.histogram_matmul_int(binned, vals, B, block_rows=64),
         H.histogram_matmul_int(binned, vals, B, block_rows=64,
                                tile_rows=tile)),
        (H.segment_histogram_int(binned, gq, hq, member, slot, S, B,
                                 levels=lv),
         H.segment_histogram_int(binned, gq, hq, member, slot, S, B,
                                 levels=lv, tile_rows=tile)),
    ]
    slot_w = jnp.where(member, slot, S)
    for pk in (None, H.pack_cols_u32_quant(binned, gq, hq, member)):
        pairs.append(
            (H.segment_histogram_sorted_int(binned, gq, hq, slot_w, S, B,
                                            packed=pk),
             H.segment_histogram_sorted_int(binned, gq, hq, slot_w, S, B,
                                            packed=pk, tile_rows=tile)))
    pairs.append(
        (H.segment_histogram_expanded_int(binned, gq, hq, member, slot_w,
                                          B, live_cap=16),
         H.segment_histogram_expanded_int(binned, gq, hq, member, slot_w,
                                          B, live_cap=16,
                                          tile_rows=tile)))
    for i, (a, b) in enumerate(pairs):
        assert np.array_equal(np.asarray(a), np.asarray(b)), f"pair {i}"


def test_tiled_compacted_dispatch():
    """tile_rows threads through the compacted wrappers (the growers'
    entry points) on both the f32 and integer paths."""
    import jax

    from lightgbm_tpu.ops import histogram as H
    from lightgbm_tpu.ops.histogram import capacity_schedule
    binned, g, h, w, slot, n, F, S, B = _tile_data()
    member = jnp.asarray((np.arange(n) % 3 == 0))
    caps = capacity_schedule(n, min_cap=256)
    a = np.asarray(H.compacted_histogram(binned, g, h, w, member, B, caps))
    b = np.asarray(H.compacted_histogram(binned, g, h, w, member, B, caps,
                                         tile_rows=128))
    assert np.array_equal(a, b)
    a = np.asarray(H.compacted_segment_histogram(
        binned, g, h, w, slot, S, B, caps))
    b = np.asarray(H.compacted_segment_histogram(
        binned, g, h, w, slot, S, B, caps, tile_rows=128))
    assert np.array_equal(a, b)
    gq, hq, _, _ = H.quantize_gradients(g, h, w, 8, jax.random.PRNGKey(0))
    lv = H.quant_levels(8)
    a = np.asarray(H.compacted_histogram_int(binned, gq, hq, w, member, B,
                                             caps, levels=lv))
    b = np.asarray(H.compacted_histogram_int(binned, gq, hq, w, member, B,
                                             caps, levels=lv,
                                             tile_rows=128))
    assert np.array_equal(a, b)
    a = np.asarray(H.compacted_segment_histogram_int(
        binned, gq, hq, w, slot, S, B, caps, levels=lv))
    b = np.asarray(H.compacted_segment_histogram_int(
        binned, gq, hq, w, slot, S, B, caps, levels=lv, tile_rows=128))
    assert np.array_equal(a, b)
