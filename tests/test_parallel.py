"""Distributed learner tests on the virtual 8-device CPU mesh.

Validates DataParallel/FeatureParallel semantics: sharded growth must
produce the SAME tree as single-device growth (the reference can only test
this with multi-machine sockets; here it's one process, 8 XLA devices).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.dataset import FeatureMeta
from lightgbm_tpu.grower import GrowerConfig, grow_tree
from lightgbm_tpu.ops.split import SplitHyperparams
from lightgbm_tpu.parallel.learners import (shard_map_compat,
                                            DATA_AXIS, FEATURE_AXIS,
                                            create_parallel_grower, make_mesh,
                                            shard_dataset)


def _meta(B, F):
    return FeatureMeta(
        num_bin=np.full(F, B, np.int32),
        missing_type=np.zeros(F, np.int32),
        default_bin=np.zeros(F, np.int32),
        most_freq_bin=np.zeros(F, np.int32),
        is_categorical=np.zeros(F, bool),
        max_num_bin=B,
    )


@pytest.fixture(scope="module")
def problem():
    rng = np.random.RandomState(0)
    n, F, B = 1024, 8, 16
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = (rng.randn(n) + 0.5 * (binned[:, 1] > 8)).astype(np.float32)
    hess = np.ones(n, np.float32)
    return binned, grad, hess, B, F


def _single_device_tree(problem, cfg, meta):
    binned, grad, hess, B, F = problem
    tree, leaf_id = grow_tree(jnp.asarray(binned.T), jnp.asarray(grad),
                              jnp.asarray(hess),
                              jnp.ones(len(grad), jnp.float32), meta, cfg)
    return tree, np.asarray(leaf_id)


def test_data_parallel_matches_serial(problem):
    binned, grad, hess, B, F = problem
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=15, hp=SplitHyperparams(min_data_in_leaf=10),
                       num_bins=B, hist_method="scatter")
    ref_tree, ref_leaf = _single_device_tree(problem, cfg, meta)

    assert jax.device_count() >= 8, "conftest must provide 8 CPU devices"
    mesh = make_mesh(8, (DATA_AXIS,))
    grower = create_parallel_grower("data", mesh, meta, cfg)
    (b, g, h, m), n_pad = shard_dataset(
        mesh, binned, grad, hess, np.ones(len(grad), np.float32))
    tree, leaf_id = grower(b, g, h, m)

    assert int(tree.num_leaves) == int(ref_tree.num_leaves)
    nl = int(tree.num_leaves)
    np.testing.assert_array_equal(np.asarray(tree.split_feature[:nl - 1]),
                                  np.asarray(ref_tree.split_feature[:nl - 1]))
    np.testing.assert_array_equal(np.asarray(tree.threshold_bin[:nl - 1]),
                                  np.asarray(ref_tree.threshold_bin[:nl - 1]))
    np.testing.assert_allclose(np.asarray(tree.leaf_value[:nl]),
                               np.asarray(ref_tree.leaf_value[:nl]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(leaf_id)[:len(ref_leaf)], ref_leaf)


def test_feature_parallel_matches_serial(problem):
    binned, grad, hess, B, F = problem
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=15, hp=SplitHyperparams(min_data_in_leaf=10),
                       num_bins=B, hist_method="scatter")
    ref_tree, ref_leaf = _single_device_tree(problem, cfg, meta)

    mesh = make_mesh(8, (FEATURE_AXIS,))
    grower = create_parallel_grower("feature", mesh, meta, cfg)
    tree, leaf_id = grower(jnp.asarray(binned.T), jnp.asarray(grad),
                           jnp.asarray(hess),
                           jnp.ones(len(grad), jnp.float32))
    assert int(tree.num_leaves) == int(ref_tree.num_leaves)
    nl = int(tree.num_leaves)
    np.testing.assert_array_equal(np.asarray(tree.split_feature[:nl - 1]),
                                  np.asarray(ref_tree.split_feature[:nl - 1]))
    np.testing.assert_allclose(np.asarray(tree.leaf_value[:nl]),
                               np.asarray(ref_tree.leaf_value[:nl]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(leaf_id), ref_leaf)


def test_2d_mesh_matches_serial(problem):
    binned, grad, hess, B, F = problem
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=7, hp=SplitHyperparams(min_data_in_leaf=10),
                       num_bins=B, hist_method="scatter")
    ref_tree, _ = _single_device_tree(problem, cfg, meta)

    mesh = make_mesh(8, (DATA_AXIS, FEATURE_AXIS), shape=(4, 2))
    grower = create_parallel_grower("data_feature", mesh, meta, cfg)
    from jax.sharding import NamedSharding, PartitionSpec as P
    b = jax.device_put(np.ascontiguousarray(binned.T),
                       NamedSharding(mesh, P(FEATURE_AXIS, DATA_AXIS)))
    g = jax.device_put(grad, NamedSharding(mesh, P(DATA_AXIS)))
    h = jax.device_put(hess, NamedSharding(mesh, P(DATA_AXIS)))
    m = jax.device_put(np.ones(len(grad), np.float32),
                       NamedSharding(mesh, P(DATA_AXIS)))
    tree, _ = grower(b, g, h, m)
    assert int(tree.num_leaves) == int(ref_tree.num_leaves)
    nl = int(tree.num_leaves)
    np.testing.assert_array_equal(np.asarray(tree.split_feature[:nl - 1]),
                                  np.asarray(ref_tree.split_feature[:nl - 1]))


# ---------------------------------------------------------------------------
# e2e: tree_learner=data|feature wired through GBDT/engine.train
# (reference dispatch: GBDT::Init -> CreateTreeLearner, gbdt.cpp:79)

def _binary_xy():
    from test_engine import EXAMPLES, _load
    return _load(f"{EXAMPLES}/binary_classification/binary.train")


def test_engine_data_parallel_end_to_end():
    import lightgbm_tpu as lgb
    X, y = _binary_xy()
    base = {"objective": "binary", "metric": "auc", "verbosity": -1,
            "num_leaves": 15, "min_data_in_leaf": 20}
    ev_s, ev_d = {}, {}

    def run(tl, ev):
        params = dict(base, tree_learner=tl)
        train = lgb.Dataset(X, label=y)
        return lgb.train(params, train, num_boost_round=10,
                         valid_sets=[lgb.Dataset(X, label=y, reference=train)],
                         evals_result=ev, verbose_eval=False)

    bst_s = run("serial", ev_s)
    bst_d = run("data", ev_d)
    assert bst_d.boosting._mesh is not None, "tree_learner=data must shard"
    assert bst_d.boosting._n_pad % 8 == 0
    # identical tree structure (gains are well separated on this data; the
    # only fp difference is psum order inside histogram bins)
    for ms, md in zip(bst_s.boosting.models, bst_d.boosting.models):
        np.testing.assert_array_equal(ms.split_feature, md.split_feature)
        np.testing.assert_array_equal(ms.threshold_in_bin, md.threshold_in_bin)
    np.testing.assert_allclose(bst_s.predict(X), bst_d.predict(X),
                               rtol=1e-4, atol=1e-5)
    assert abs(ev_s["valid_0"]["auc"][-1] - ev_d["valid_0"]["auc"][-1]) < 1e-3


def test_engine_feature_parallel_end_to_end():
    import lightgbm_tpu as lgb
    X, y = _binary_xy()
    base = {"objective": "binary", "metric": "auc", "verbosity": -1,
            "num_leaves": 15, "min_data_in_leaf": 20,
            "enable_bundle": False}

    def run(tl):
        params = dict(base, tree_learner=tl)
        return lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)

    bst_s = run("serial")
    bst_f = run("feature")
    assert bst_f.boosting._mesh is not None
    for ms, mf in zip(bst_s.boosting.models, bst_f.boosting.models):
        np.testing.assert_array_equal(ms.split_feature, mf.split_feature)
        np.testing.assert_array_equal(ms.threshold_in_bin, mf.threshold_in_bin)
    np.testing.assert_allclose(bst_s.predict(X), bst_f.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_engine_feature_parallel_with_efb_matches_serial():
    """Feature sharding composes with EFB by partitioning whole BUNDLES
    (reference partitions features after bundling,
    feature_parallel_tree_learner.cpp:33-52): sparse one-hot-ish columns
    bundle into shared group columns, groups are packed shard-major, and
    the result must match serial training exactly."""
    rng = np.random.RandomState(0)
    n = 500
    groups = rng.randint(0, 8, size=n)
    X = np.zeros((n, 8), np.float32)
    X[np.arange(n), groups] = rng.rand(n) + 0.5
    X = np.concatenate([X, rng.rand(n, 4).astype(np.float32)], axis=1)
    y = ((groups % 2) ^ (X[:, 8] > 0.5)).astype(np.float32)
    import lightgbm_tpu as lgb
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    assert ds.feature_meta().resolved().has_bundles, "test premise: EFB fires"
    base = {"objective": "binary", "verbosity": -1, "min_data_in_leaf": 5,
            "num_leaves": 15}
    bst_s = lgb.train(dict(base, tree_learner="serial"),
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bst_f = lgb.train(dict(base, tree_learner="feature"),
                      lgb.Dataset(X, label=y), num_boost_round=6)
    assert bst_f.boosting._mesh is not None
    assert bst_f.boosting._feat_perm is not None, "EFB shard layout in use"
    for ms, mf in zip(bst_s.boosting.models, bst_f.boosting.models):
        np.testing.assert_array_equal(ms.split_feature, mf.split_feature)
        np.testing.assert_array_equal(ms.threshold_in_bin, mf.threshold_in_bin)
        np.testing.assert_allclose(ms.leaf_value, mf.leaf_value,
                                   rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(bst_s.predict(X), bst_f.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_engine_data_parallel_bagging_goss_l1():
    """Distributed modes compose with bagging masks, GOSS and L1 renewal."""
    import lightgbm_tpu as lgb
    X, y = _binary_xy()
    cases = [
        {"objective": "binary", "bagging_freq": 1, "bagging_fraction": 0.7},
        {"objective": "binary", "boosting": "goss"},
        {"objective": "regression_l1"},
    ]
    for extra in cases:
        params = dict({"metric": "None", "verbosity": -1, "num_leaves": 7,
                       "min_data_in_leaf": 20, "tree_learner": "data"}, **extra)
        bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
        p = bst.predict(X)
        assert np.isfinite(p).all()
        assert bst.boosting.num_trees() == 5


# ---------------------------------------------------------------------------
# voting-parallel (PV-Tree, reference voting_parallel_tree_learner.cpp)

def test_engine_voting_parallel_matches_serial_at_full_topk():
    # top_k >= num_features: the election keeps every feature, so voting
    # must agree with serial exactly (module histogram psum fp order)
    import lightgbm_tpu as lgb
    X, y = _binary_xy()
    base = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
            "min_data_in_leaf": 20}

    bst_s = lgb.train(dict(base, tree_learner="serial"),
                      lgb.Dataset(X, label=y), num_boost_round=8)
    bst_v = lgb.train(dict(base, tree_learner="voting", top_k=X.shape[1]),
                      lgb.Dataset(X, label=y), num_boost_round=8)
    assert bst_v.boosting._mesh is not None
    assert bst_v.boosting.grower_cfg.voting_top_k == X.shape[1]
    for ms, mv in zip(bst_s.boosting.models, bst_v.boosting.models):
        np.testing.assert_array_equal(ms.split_feature, mv.split_feature)
        np.testing.assert_array_equal(ms.threshold_in_bin, mv.threshold_in_bin)
    np.testing.assert_allclose(bst_s.predict(X), bst_v.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_engine_voting_parallel_small_topk_trains():
    import lightgbm_tpu as lgb
    X, y = _binary_xy()
    evals = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "metric": "auc", "verbosity": -1,
                     "num_leaves": 15, "min_data_in_leaf": 20,
                     "tree_learner": "voting", "top_k": 5},
                    train, num_boost_round=10,
                    valid_sets=[lgb.Dataset(X, label=y, reference=train)],
                    evals_result=evals, verbose_eval=False)
    # approximate mode must still learn (reference PV-Tree claim);
    # serial at this config measures 0.7866, voting top_k=5 0.7869
    assert evals["valid_0"]["auc"][-1] > 0.77


def _allreduce_f32_elems(hlo_text):
    """Sum of f32 element counts over all all-reduce ops in an HLO dump."""
    import re
    total = 0
    for m in re.finditer(r"f32\[([0-9,]*)\][^=]*all-reduce", hlo_text):
        dims = m.group(1)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n
    return total


def test_voting_parallel_reduces_histogram_traffic(problem):
    """The vote exchanges [top_k, B, 3] histograms instead of [F, B, 3]."""
    import functools
    binned, grad, hess, B, F = problem
    meta = _meta(B, F)
    mesh = make_mesh(8, (DATA_AXIS,))

    def lower(cfg):
        @functools.partial(
            shard_map_compat, mesh=mesh,
            in_specs=(jax.sharding.PartitionSpec(None, DATA_AXIS),)
            + (jax.sharding.PartitionSpec(DATA_AXIS),) * 3,
            out_specs=(jax.sharding.PartitionSpec(),
                       jax.sharding.PartitionSpec(DATA_AXIS)),
            check_vma=False)
        def step(b, g, h, m):
            return grow_tree(b, g, h, m, meta, cfg, axis_name=DATA_AXIS)
        (b,), _ = shard_dataset(mesh, binned)
        args, _ = shard_dataset(mesh, binned, grad, hess,
                                np.ones(len(grad), np.float32))
        return jax.jit(step).lower(*args).compile().as_text()

    hp = SplitHyperparams(min_data_in_leaf=10)
    data_cfg = GrowerConfig(num_leaves=7, hp=hp, num_bins=B,
                            hist_method="scatter")
    vote_cfg = GrowerConfig(num_leaves=7, hp=hp, num_bins=B,
                            hist_method="scatter", voting_top_k=2,
                            num_machines=8)
    data_traffic = _allreduce_f32_elems(lower(data_cfg))
    vote_traffic = _allreduce_f32_elems(lower(vote_cfg))
    assert vote_traffic < data_traffic, (vote_traffic, data_traffic)


def test_engine_feature_parallel_monotone_matches_serial():
    # regression guard: bound propagation must index constraints by GLOBAL
    # feature id even when the scan slices them per feature shard
    import lightgbm_tpu as lgb
    X, y = _binary_xy()
    base = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
            "min_data_in_leaf": 20, "enable_bundle": False,
            "monotone_constraints": [1, -1] * 14}
    bst_s = lgb.train(dict(base, tree_learner="serial"),
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bst_f = lgb.train(dict(base, tree_learner="feature"),
                      lgb.Dataset(X, label=y), num_boost_round=6)
    for ms, mf in zip(bst_s.boosting.models, bst_f.boosting.models):
        np.testing.assert_array_equal(ms.split_feature, mf.split_feature)
        np.testing.assert_allclose(ms.leaf_value, mf.leaf_value,
                                   rtol=1e-4, atol=1e-6)


def _ranking_xy(n_queries=60, seed=7):
    """Synthetic LTR data: queries of varying size with graded labels."""
    rng = np.random.RandomState(seed)
    sizes = rng.randint(5, 40, n_queries)
    Xs, ys, group = [], [], []
    for s in sizes:
        Xq = rng.rand(s, 6)
        rel = (2.0 * Xq[:, 0] + Xq[:, 1] + 0.3 * rng.randn(s))
        yq = np.clip(np.digitize(rel, [0.8, 1.5, 2.2]), 0, 3)
        Xs.append(Xq)
        ys.append(yq)
        group.append(s)
    return (np.concatenate(Xs), np.concatenate(ys).astype(np.float64),
            np.asarray(group, np.int64))


@pytest.mark.parametrize("objective", ["lambdarank", "rank_xendcg"])
def test_engine_data_parallel_ranking_matches_serial(objective):
    """Distributed ranking via query-aligned row sharding: whole queries
    per shard, per-query lambdas shard-local by construction (reference:
    Metadata::CheckOrPartition partitions at query boundaries,
    src/io/metadata.cpp:141)."""
    import lightgbm_tpu as lgb
    X, y, group = _ranking_xy()
    base = {"objective": objective, "metric": "ndcg", "ndcg_eval_at": [5],
            "verbosity": -1, "num_leaves": 15, "min_data_in_leaf": 10,
            "objective_seed": 11}
    ev_s, ev_d = {}, {}

    def run(tl, ev):
        params = dict(base, tree_learner=tl)
        train = lgb.Dataset(X, label=y, group=group)
        valid = lgb.Dataset(X, label=y, group=group, reference=train)
        return lgb.train(params, train, num_boost_round=8,
                         valid_sets=[valid], evals_result=ev,
                         verbose_eval=False)

    bst_s = run("serial", ev_s)
    bst_d = run("data", ev_d)
    assert bst_d.boosting._mesh is not None, "tree_learner=data must shard"
    assert bst_d.boosting._row_perm is not None, "query-aligned layout"
    # no query may straddle a shard boundary
    perm = bst_d.boosting._row_perm
    n = len(y)
    n_shard = len(perm) // 8
    qb = np.concatenate([[0], np.cumsum(group)])
    starts = {int(s): i for i, s in enumerate(qb[:-1])}
    for d in range(8):
        chunk = perm[d * n_shard:(d + 1) * n_shard]
        rows = chunk[chunk < n]
        # rows of one shard = union of complete queries
        covered = 0
        while covered < len(rows):
            q = starts[int(rows[covered])]
            covered += int(qb[q + 1] - qb[q])
        assert covered == len(rows)
    for ms, md in zip(bst_s.boosting.models, bst_d.boosting.models):
        np.testing.assert_array_equal(ms.split_feature, md.split_feature)
        np.testing.assert_array_equal(ms.threshold_in_bin, md.threshold_in_bin)
    np.testing.assert_allclose(bst_s.predict(X), bst_d.predict(X),
                               rtol=1e-4, atol=1e-5)
    assert abs(ev_s["valid_0"]["ndcg@5"][-1]
               - ev_d["valid_0"]["ndcg@5"][-1]) < 1e-3


def test_network_machine_list_mapping():
    """Reference machine-list configs map onto jax.distributed wiring
    (parallel/network.py; reference linkers_socket.cpp:23-76)."""
    import socket
    from lightgbm_tpu.parallel.network import (init_network,
                                               parse_machine_list,
                                               resolve_rank)
    ml = parse_machine_list("10.0.0.1:12400,10.0.0.2:12401")
    assert ml == [("10.0.0.1", 12400), ("10.0.0.2", 12401)]
    host = socket.gethostname()
    ml2 = parse_machine_list(f"10.0.0.1:12400,{host}:12401")
    assert resolve_rank(ml2) == 1
    out = init_network(machines=f"10.0.0.1:12400,{host}:12401",
                       num_machines=2, dry_run=True)
    assert out == ("10.0.0.1:12400", 2, 1)
    # multi-process-per-host: port disambiguates
    ml3 = parse_machine_list(f"{host}:12400,{host}:12401")
    assert resolve_rank(ml3, local_listen_port=12401) == 1
    import pytest
    with pytest.raises(ValueError):
        resolve_rank([("10.9.9.9", 1)])


# ---------------------------------------------------------------------------
# learner-combination matrix: CEGB and forced splits compose with the
# distributed learners (the reference wires both through SerialTreeLearner
# hooks shared by every learner, serial_tree_learner.cpp:65-68,411-521,
# 529-532; here the sharded growers must match serial exactly)

def _struct_match(a, b):
    assert len(a.boosting.models) == len(b.boosting.models)
    for ms, mf in zip(a.boosting.models, b.boosting.models):
        np.testing.assert_array_equal(ms.split_feature, mf.split_feature)
        np.testing.assert_array_equal(ms.threshold_in_bin,
                                      mf.threshold_in_bin)


def test_cegb_feature_parallel_matches_serial():
    import lightgbm_tpu as lgb
    X, y = _binary_xy()
    base = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
            "min_data_in_leaf": 20, "enable_bundle": False,
            "cegb_penalty_split": 0.002,
            "cegb_penalty_feature_coupled": [0.3] * X.shape[1]}
    bst_s = lgb.train(dict(base, tree_learner="serial"),
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bst_f = lgb.train(dict(base, tree_learner="feature"),
                      lgb.Dataset(X, label=y), num_boost_round=6)
    assert bst_f.boosting._mesh is not None
    # the penalties actually bit: the CEGB model must differ from plain
    plain = lgb.train({k: v for k, v in base.items()
                       if not k.startswith("cegb")},
                      lgb.Dataset(X, label=y), num_boost_round=6)
    assert not np.allclose(plain.predict(X), bst_s.predict(X)), \
        "test premise: CEGB penalties changed the model"
    _struct_match(bst_s, bst_f)
    np.testing.assert_allclose(bst_s.predict(X), bst_f.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_cegb_lazy_feature_parallel_matches_serial():
    import lightgbm_tpu as lgb
    X, y = _binary_xy()
    base = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
            "min_data_in_leaf": 20, "enable_bundle": False,
            "cegb_penalty_feature_lazy": [0.004] * X.shape[1]}
    bst_s = lgb.train(dict(base, tree_learner="serial"),
                      lgb.Dataset(X, label=y), num_boost_round=5)
    bst_f = lgb.train(dict(base, tree_learner="feature"),
                      lgb.Dataset(X, label=y), num_boost_round=5)
    _struct_match(bst_s, bst_f)
    np.testing.assert_allclose(bst_s.predict(X), bst_f.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_cegb_feature_parallel_with_efb_matches_serial():
    """CEGB under the sharded-EFB layout: penalties/used-state ride in
    device-slot order (padded, permuted) and must still match serial."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    n = 500
    groups = rng.randint(0, 8, size=n)
    X = np.zeros((n, 8), np.float32)
    X[np.arange(n), groups] = rng.rand(n) + 0.5
    X = np.concatenate([X, rng.rand(n, 4).astype(np.float32)], axis=1)
    y = ((groups % 2) ^ (X[:, 8] > 0.5)).astype(np.float32)
    base = {"objective": "binary", "verbosity": -1, "min_data_in_leaf": 5,
            "num_leaves": 15,
            "cegb_penalty_feature_coupled": [0.2] * X.shape[1]}
    bst_s = lgb.train(dict(base, tree_learner="serial"),
                      lgb.Dataset(X, label=y), num_boost_round=5)
    bst_f = lgb.train(dict(base, tree_learner="feature"),
                      lgb.Dataset(X, label=y), num_boost_round=5)
    assert bst_f.boosting._feat_perm is not None, "EFB shard layout in use"
    _struct_match(bst_s, bst_f)
    np.testing.assert_allclose(bst_s.predict(X), bst_f.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_cegb_data_parallel_matches_serial():
    import lightgbm_tpu as lgb
    X, y = _binary_xy()
    base = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
            "min_data_in_leaf": 20, "cegb_penalty_split": 0.002,
            "cegb_penalty_feature_lazy": [0.002] * X.shape[1]}
    bst_s = lgb.train(dict(base, tree_learner="serial"),
                      lgb.Dataset(X, label=y), num_boost_round=5)
    bst_d = lgb.train(dict(base, tree_learner="data"),
                      lgb.Dataset(X, label=y), num_boost_round=5)
    _struct_match(bst_s, bst_d)
    np.testing.assert_allclose(bst_s.predict(X), bst_d.predict(X),
                               rtol=1e-4, atol=1e-5)


def _forced_json(tmp_path, spec):
    import json
    import os
    fn = os.path.join(str(tmp_path), "forced.json")
    with open(fn, "w") as f:
        json.dump(spec, f)
    return fn


def test_forced_splits_feature_parallel_matches_serial(tmp_path):
    import lightgbm_tpu as lgb
    X, y = _binary_xy()
    fn = _forced_json(tmp_path, {
        "feature": 3, "threshold": 0.5,
        "left": {"feature": 1, "threshold": 0.4}})
    base = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
            "min_data_in_leaf": 20, "enable_bundle": False,
            "forcedsplits_filename": fn}
    bst_s = lgb.train(dict(base, tree_learner="serial"),
                      lgb.Dataset(X, label=y), num_boost_round=5)
    bst_f = lgb.train(dict(base, tree_learner="feature"),
                      lgb.Dataset(X, label=y), num_boost_round=5)
    # forced structure honored: root split on feature 3
    for m in bst_s.boosting.models:
        assert int(m.split_feature[0]) == 3
    _struct_match(bst_s, bst_f)
    np.testing.assert_allclose(bst_s.predict(X), bst_f.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_forced_splits_voting_parallel_matches_serial(tmp_path):
    import lightgbm_tpu as lgb
    X, y = _binary_xy()
    fn = _forced_json(tmp_path, {"feature": 2, "threshold": 0.6})
    base = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
            "min_data_in_leaf": 20, "forcedsplits_filename": fn}
    bst_s = lgb.train(dict(base, tree_learner="serial"),
                      lgb.Dataset(X, label=y), num_boost_round=5)
    bst_v = lgb.train(dict(base, tree_learner="voting", top_k=X.shape[1]),
                      lgb.Dataset(X, label=y), num_boost_round=5)
    assert bst_v.boosting.grower_cfg.voting_top_k == X.shape[1]
    for m in bst_v.boosting.models:
        assert int(m.split_feature[0]) == 2
    _struct_match(bst_s, bst_v)
    np.testing.assert_allclose(bst_s.predict(X), bst_v.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_cegb_voting_raises_with_rationale():
    """CEGB x voting is a recorded design exclusion (exact CEGB needs the
    global per-feature candidates voting exists to avoid building)."""
    import pytest

    import lightgbm_tpu as lgb
    X, y = _binary_xy()
    with pytest.raises(NotImplementedError, match="tree_learner=data"):
        lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 7,
                   "tree_learner": "voting", "top_k": 3,
                   "cegb_penalty_split": 0.01},
                  lgb.Dataset(X, label=y), num_boost_round=1)
