"""Distributed learner tests on the virtual 8-device CPU mesh.

Validates DataParallel/FeatureParallel semantics: sharded growth must
produce the SAME tree as single-device growth (the reference can only test
this with multi-machine sockets; here it's one process, 8 XLA devices).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.dataset import FeatureMeta
from lightgbm_tpu.grower import GrowerConfig, grow_tree
from lightgbm_tpu.ops.split import SplitHyperparams
from lightgbm_tpu.parallel.learners import (DATA_AXIS, FEATURE_AXIS,
                                            create_parallel_grower, make_mesh,
                                            shard_dataset)


def _meta(B, F):
    return FeatureMeta(
        num_bin=np.full(F, B, np.int32),
        missing_type=np.zeros(F, np.int32),
        default_bin=np.zeros(F, np.int32),
        most_freq_bin=np.zeros(F, np.int32),
        is_categorical=np.zeros(F, bool),
        max_num_bin=B,
    )


@pytest.fixture(scope="module")
def problem():
    rng = np.random.RandomState(0)
    n, F, B = 1024, 8, 16
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = (rng.randn(n) + 0.5 * (binned[:, 1] > 8)).astype(np.float32)
    hess = np.ones(n, np.float32)
    return binned, grad, hess, B, F


def _single_device_tree(problem, cfg, meta):
    binned, grad, hess, B, F = problem
    tree, leaf_id = grow_tree(jnp.asarray(binned), jnp.asarray(grad),
                              jnp.asarray(hess),
                              jnp.ones(len(grad), jnp.float32), meta, cfg)
    return tree, np.asarray(leaf_id)


def test_data_parallel_matches_serial(problem):
    binned, grad, hess, B, F = problem
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=15, hp=SplitHyperparams(min_data_in_leaf=10),
                       num_bins=B, hist_method="scatter")
    ref_tree, ref_leaf = _single_device_tree(problem, cfg, meta)

    assert jax.device_count() >= 8, "conftest must provide 8 CPU devices"
    mesh = make_mesh(8, (DATA_AXIS,))
    grower = create_parallel_grower("data", mesh, meta, cfg)
    (b, g, h, m), n_pad = shard_dataset(
        mesh, binned, grad, hess, np.ones(len(grad), np.float32))
    tree, leaf_id = grower(b, g, h, m)

    assert int(tree.num_leaves) == int(ref_tree.num_leaves)
    nl = int(tree.num_leaves)
    np.testing.assert_array_equal(np.asarray(tree.split_feature[:nl - 1]),
                                  np.asarray(ref_tree.split_feature[:nl - 1]))
    np.testing.assert_array_equal(np.asarray(tree.threshold_bin[:nl - 1]),
                                  np.asarray(ref_tree.threshold_bin[:nl - 1]))
    np.testing.assert_allclose(np.asarray(tree.leaf_value[:nl]),
                               np.asarray(ref_tree.leaf_value[:nl]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(leaf_id)[:len(ref_leaf)], ref_leaf)


def test_feature_parallel_matches_serial(problem):
    binned, grad, hess, B, F = problem
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=15, hp=SplitHyperparams(min_data_in_leaf=10),
                       num_bins=B, hist_method="scatter")
    ref_tree, ref_leaf = _single_device_tree(problem, cfg, meta)

    mesh = make_mesh(8, (FEATURE_AXIS,))
    grower = create_parallel_grower("feature", mesh, meta, cfg)
    tree, leaf_id = grower(jnp.asarray(binned), jnp.asarray(grad),
                           jnp.asarray(hess),
                           jnp.ones(len(grad), jnp.float32))
    assert int(tree.num_leaves) == int(ref_tree.num_leaves)
    nl = int(tree.num_leaves)
    np.testing.assert_array_equal(np.asarray(tree.split_feature[:nl - 1]),
                                  np.asarray(ref_tree.split_feature[:nl - 1]))
    np.testing.assert_allclose(np.asarray(tree.leaf_value[:nl]),
                               np.asarray(ref_tree.leaf_value[:nl]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(leaf_id), ref_leaf)


def test_2d_mesh_matches_serial(problem):
    binned, grad, hess, B, F = problem
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=7, hp=SplitHyperparams(min_data_in_leaf=10),
                       num_bins=B, hist_method="scatter")
    ref_tree, _ = _single_device_tree(problem, cfg, meta)

    mesh = make_mesh(8, (DATA_AXIS, FEATURE_AXIS), shape=(4, 2))
    grower = create_parallel_grower("data_feature", mesh, meta, cfg)
    from jax.sharding import NamedSharding, PartitionSpec as P
    b = jax.device_put(binned, NamedSharding(mesh, P(DATA_AXIS, FEATURE_AXIS)))
    g = jax.device_put(grad, NamedSharding(mesh, P(DATA_AXIS)))
    h = jax.device_put(hess, NamedSharding(mesh, P(DATA_AXIS)))
    m = jax.device_put(np.ones(len(grad), np.float32),
                       NamedSharding(mesh, P(DATA_AXIS)))
    tree, _ = grower(b, g, h, m)
    assert int(tree.num_leaves) == int(ref_tree.num_leaves)
    nl = int(tree.num_leaves)
    np.testing.assert_array_equal(np.asarray(tree.split_feature[:nl - 1]),
                                  np.asarray(ref_tree.split_feature[:nl - 1]))
