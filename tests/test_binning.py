"""BinMapper semantics tests (reference: src/io/bin.cpp FindBin)."""

import numpy as np
import pytest

from lightgbm_tpu.binning import BinMapper, BinType, MissingType


def test_simple_uniform_binning():
    rng = np.random.RandomState(0)
    vals = rng.rand(1000) + 0.5  # all positive, no zeros
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=1000, max_bin=16, min_data_in_bin=3)
    assert 2 <= m.num_bin <= 16
    assert m.missing_type == MissingType.NONE
    bins = m.value_to_bin(vals)
    assert bins.min() >= 0 and bins.max() < m.num_bin
    # monotone: larger values -> same or larger bin
    order = np.argsort(vals)
    assert (np.diff(bins[order]) >= 0).all()


def test_upper_bounds_are_inclusive():
    m = BinMapper()
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0] * 10)
    m.find_bin(vals, total_sample_cnt=50, max_bin=5, min_data_in_bin=1)
    for b in range(m.num_bin - 1):
        ub = m.bin_upper_bound[b]
        if np.isfinite(ub):
            assert m.value_to_bin(np.array([ub]))[0] == b
            assert m.value_to_bin(np.array([np.nextafter(ub, np.inf)]))[0] == b + 1


def test_zero_bin_and_negative():
    vals = np.array([-2.0, -1.0, 1.0, 2.0] * 25)
    # 100 stored values of 200 rows -> 100 implicit zeros
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=200, max_bin=10, min_data_in_bin=1)
    zb = m.value_to_bin(np.array([0.0]))[0]
    assert m.default_bin == zb
    assert m.value_to_bin(np.array([-1.0]))[0] < zb
    assert m.value_to_bin(np.array([1.0]))[0] > zb


def test_nan_missing_type():
    vals = np.array([1.0, 2.0, 3.0, np.nan, np.nan] * 20)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=100, max_bin=10, min_data_in_bin=1)
    assert m.missing_type == MissingType.NAN
    assert m.value_to_bin(np.array([np.nan]))[0] == m.num_bin - 1


def test_zero_as_missing():
    vals = np.array([1.0, 2.0, 3.0, 4.0] * 20)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=200, max_bin=10, min_data_in_bin=1,
               zero_as_missing=True)
    assert m.missing_type == MissingType.ZERO


def test_trivial_feature():
    vals = np.ones(0)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=100, max_bin=10, min_data_in_bin=1)
    assert m.is_trivial


def test_categorical_count_sort():
    # category 3 most frequent, then 1, then 7
    vals = np.array([3.0] * 50 + [1.0] * 30 + [7.0] * 20)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=100, max_bin=10, min_data_in_bin=1,
               bin_type=BinType.CATEGORICAL)
    assert m.bin_type == BinType.CATEGORICAL
    assert m.bin_2_categorical[0] == 3
    assert m.value_to_bin(np.array([3.0]))[0] == 0
    assert m.value_to_bin(np.array([1.0]))[0] == 1
    assert m.value_to_bin(np.array([7.0]))[0] == 2
    # unseen category -> last bin
    assert m.value_to_bin(np.array([99.0]))[0] == m.num_bin - 1


def test_categorical_zero_not_first_bin():
    vals = np.array([0.0] * 50 + [1.0] * 30)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=80, max_bin=10, min_data_in_bin=1,
               bin_type=BinType.CATEGORICAL)
    # reference avoids bin0 == category 0 (bin.cpp:459-466)
    assert m.bin_2_categorical[0] != 0


def test_min_data_in_bin_respected():
    vals = np.concatenate([np.full(5, i, float) for i in range(1, 21)])
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=100, max_bin=100, min_data_in_bin=10)
    # 20 distinct values x5 rows with min 10/bin -> bins hold >= 2 values
    assert m.num_bin <= 11


def test_serialization_roundtrip():
    rng = np.random.RandomState(1)
    vals = np.concatenate([rng.randn(500), [np.nan] * 20])
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=600, max_bin=32, min_data_in_bin=3)
    m2 = BinMapper.from_dict(m.to_dict())
    test_vals = np.concatenate([rng.randn(100), [np.nan, 0.0]])
    np.testing.assert_array_equal(m.value_to_bin(test_vals), m2.value_to_bin(test_vals))


def test_efb_bundling_exact_parity():
    """Mutually-exclusive one-hot features bundle into few columns and give
    IDENTICAL models to enable_bundle=false (zero conflicts -> EFB exact).
    reference: Dataset::FindGroups / FastFeatureBundling (dataset.cpp:97-313).
    """
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    n = 3000
    cat = rng.randint(0, 12, n)
    onehot = np.zeros((n, 12), np.float64)
    onehot[np.arange(n), cat] = 1.0
    dense = rng.randn(n, 3)
    X = np.column_stack([onehot, dense])
    y = ((cat % 3 == 0) * 1.0 + 0.4 * dense[:, 0] + 0.2 * rng.randn(n) > 0.5)

    ds_on = lgb.Dataset(X, label=y.astype(np.float64),
                        params={"min_data_in_leaf": 5})
    ds_on.construct()
    ds_off = lgb.Dataset(X, label=y.astype(np.float64),
                         params={"enable_bundle": False,
                                 "min_data_in_leaf": 5})
    ds_off.construct()
    # the 12 one-hot columns must share a handful of merged columns
    assert ds_on.num_groups < ds_off.num_groups == len(ds_off.used_features)
    assert ds_on.binned.shape[1] == ds_on.num_groups

    params = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
              "min_data_in_leaf": 5}
    b_on = lgb.train(params, ds_on, num_boost_round=8, verbose_eval=False)
    b_off = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y.astype(np.float64),
                                  params={"enable_bundle": False,
                                          "min_data_in_leaf": 5}),
                      num_boost_round=8, verbose_eval=False)
    # bundled histograms reconstruct the shared default bin from f32 leaf
    # totals (FixHistogram), so gains match only to float precision; the
    # FIRST tree must still pick identical splits, and model quality match.
    t_on, t_off = b_on.boosting.models[0], b_off.boosting.models[0]
    np.testing.assert_array_equal(t_on.split_feature, t_off.split_feature)
    np.testing.assert_allclose(t_on.threshold, t_off.threshold, rtol=1e-6)
    p_on = b_on.predict(X)
    p_off = b_off.predict(X)
    from sklearn.metrics import log_loss
    assert abs(log_loss(y, p_on) - log_loss(y, p_off)) < 1e-3


def test_efb_binary_cache_roundtrip(tmp_path):
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(1)
    n = 500
    onehot = np.eye(8)[rng.randint(0, 8, n)]
    X = np.column_stack([onehot, rng.randn(n, 2)])
    y = (onehot[:, 0] + rng.randn(n) * 0.1 > 0.5).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5})
    ds.construct()
    path = str(tmp_path / "efb.bin")
    ds.save_binary(path)
    ds2 = lgb.Dataset.load_binary(path)
    np.testing.assert_array_equal(ds.binned, ds2.binned)
    np.testing.assert_array_equal(ds.feat_group, ds2.feat_group)
    np.testing.assert_array_equal(ds.feat_start, ds2.feat_start)
    bst = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 7,
                     "min_data_in_leaf": 5}, ds2, num_boost_round=3,
                    verbose_eval=False)
    assert bst.num_trees() == 3


def test_distinct_collapse_vectorized_matches_loop():
    """find_bin's vectorized distinct-value collapse must reproduce the
    reference scalar loop (bin.cpp:358-390 semantics) exactly, including
    the zero-group splices."""
    import math

    def loop_collapse(values, zero_cnt):
        dv, ct = [], []
        if len(values) == 0 or (values[0] > 0.0 and zero_cnt > 0):
            dv.append(0.0)
            ct.append(zero_cnt)
        if len(values) > 0:
            dv.append(float(values[0]))
            ct.append(1)
        for i in range(1, len(values)):
            prev, cur = float(values[i - 1]), float(values[i])
            if not (cur <= math.nextafter(prev, math.inf)):
                if prev < 0.0 and cur > 0.0:
                    dv.append(0.0)
                    ct.append(zero_cnt)
                dv.append(cur)
                ct.append(1)
            else:
                dv[-1] = cur
                ct[-1] += 1
        if len(values) > 0 and float(values[-1]) < 0.0 and zero_cnt > 0:
            dv.append(0.0)
            ct.append(zero_cnt)
        return dv, ct

    rng = np.random.RandomState(0)
    cases = []
    for sign in ((-1, 1), (1, 1), (-1, -1)):
        v = np.sort(np.concatenate([
            sign[0] * rng.rand(500), sign[1] * rng.rand(500),
            np.repeat(sign[1] * rng.rand(50), 7)]))
        v = v[np.abs(v) > 1e-35]
        cases.append(v)
    cases.append(np.array([], np.float64))
    cases.append(np.sort(rng.randn(1000)))  # ties unlikely, mixed sign
    for vals in cases:
        for zero_cnt in (0, 17):
            m = BinMapper()
            m.find_bin(vals.copy(), len(vals) + zero_cnt, 63,
                       min_data_in_bin=3)
            dv, ct = loop_collapse(np.sort(vals), zero_cnt)
            # reproduce through the public result: bins from the loop's
            # collapse must equal bins from the vectorized one.  Build
            # the expected bounds by calling the module's greedy path on
            # the loop-collapsed arrays.
            from lightgbm_tpu.binning import _find_bin_with_zero_as_one_bin
            if dv:
                expect = _find_bin_with_zero_as_one_bin(
                    np.asarray(dv), np.asarray(ct), 63,
                    len(vals) + zero_cnt, 3)
                np.testing.assert_array_equal(
                    m.bin_upper_bound, np.asarray(expect, np.float64))


def test_cnt_in_bin_lag_matches_reference_loop():
    """The reference advances its cnt_in_bin cursor at most once per
    distinct value (bin.cpp); with forced bounds creating empty leading
    bins the counts LAG into earlier bins.  The vectorized closed form
    must mirror that lag exactly (it feeds NeedFilter and most_freq_bin),
    and, without forced bounds, match the unlagged assignment."""
    import math

    def loop_counts(dv, ct, ub, num_bin):
        cnt = [0] * num_bin
        i_bin = 0
        for i in range(len(dv)):
            if dv[i] > ub[i_bin]:
                i_bin += 1
            cnt[i_bin] += int(ct[i])
        return cnt

    rng = np.random.RandomState(1)
    # forced bounds far below the data -> two empty leading bins
    vals = (10.0 + 3.0 * rng.rand(2000)).astype(np.float64)
    m = BinMapper()
    m.find_bin(vals, len(vals), 16, min_data_in_bin=3,
               forced_upper_bounds=[1.0, 2.0])
    # reconstruct distinct values exactly as find_bin does
    sv = np.sort(vals)
    newgrp = sv[1:] > np.nextafter(sv[:-1], np.inf)
    ends = np.append(np.nonzero(newgrp)[0], len(sv) - 1)
    dv = sv[ends]
    ct = np.diff(np.append(-1, ends))
    expect = loop_counts(dv, ct, m.bin_upper_bound, m.num_bin)
    # observable effect: most_freq_bin = argmax(cnt) unless its share is
    # below the sparse threshold, in which case it falls back to
    # default_bin (reference bin.cpp tail)
    mf = int(np.argmax(expect))
    if mf != m.default_bin and expect[mf] / len(vals) < 0.8:
        mf = m.default_bin
    assert m.most_freq_bin == mf
    assert abs(m.sparse_rate - expect[m.most_freq_bin] / len(vals)) < 1e-12
    true_idx = np.minimum(
        np.searchsorted(m.bin_upper_bound[:m.num_bin], dv, side="left"),
        m.num_bin - 1)
    lag = np.arange(len(dv))
    i_bin = np.minimum(lag + 1,
                       lag + np.minimum.accumulate(true_idx - lag))
    got = np.bincount(i_bin, weights=ct, minlength=m.num_bin)
    np.testing.assert_array_equal(got.astype(int), expect)
    # random fuzz without forced bounds: lagged == unlagged there
    rng2 = np.random.RandomState(7)
    for _ in range(20):
        v2 = rng2.randn(rng2.randint(5, 400)) * 10 ** rng2.randint(-3, 3)
        v2 = v2[np.abs(v2) > 1e-35]
        m3 = BinMapper()
        m3.find_bin(v2.copy(), len(v2) + 3, 12, min_data_in_bin=2)
        if m3.is_trivial:
            continue
        sv2 = np.sort(v2)
        ng = sv2[1:] > np.nextafter(sv2[:-1], np.inf)
        e2 = np.append(np.nonzero(ng)[0], len(sv2) - 1)
        dv2 = sv2[e2].tolist()
        ct2 = np.diff(np.append(-1, e2)).tolist()
        if sv2[0] > 0.0:
            dv2.insert(0, 0.0); ct2.insert(0, 3)
        elif sv2[-1] < 0.0:
            dv2.append(0.0); ct2.append(3)
        else:
            zp = int(np.searchsorted(sv2[e2], 0.0))
            dv2.insert(zp, 0.0); ct2.insert(zp, 3)
        nb_real = (m3.num_bin - 1 if m3.missing_type == 2 else m3.num_bin)
        exp2 = loop_counts(np.asarray(dv2), np.asarray(ct2),
                           m3.bin_upper_bound[:nb_real], nb_real)
        tot = len(v2) + 3
        mf2 = int(np.argmax(exp2))
        if mf2 != m3.default_bin and exp2[mf2] / tot < 0.8:
            mf2 = m3.default_bin
        assert m3.most_freq_bin == mf2


def test_native_greedy_find_bin_matches_python():
    """native/findbin.cpp must reproduce the Python GreedyFindBin mirror
    bit-for-bit (both mirror reference bin.cpp:77-155)."""
    from lightgbm_tpu.binning import (_greedy_find_bin_native,
                                      greedy_find_bin)
    from lightgbm_tpu.native.build import load_native_lib
    if load_native_lib() is None:
        import pytest
        pytest.skip("native toolchain unavailable")
    rng = np.random.RandomState(0)
    for trial in range(30):
        nd = rng.randint(600, 5000)       # above the native-dispatch gate
        dv = np.sort(rng.randn(nd) * 10 ** rng.randint(-2, 3))
        dv = np.unique(dv)
        ct = rng.randint(1, 50, size=len(dv)).astype(np.int64)
        # spike some counts so is_big paths trigger
        ct[rng.randint(0, len(dv), 5)] += rng.randint(100, 10000)
        total = int(ct.sum())
        mb = int(rng.choice([15, 63, 255]))
        mdib = int(rng.choice([0, 1, 3, 20]))
        nat = _greedy_find_bin_native(dv, ct, mb, total, mdib)
        # the Python fallback is reached by stubbing the native hook out
        import lightgbm_tpu.binning as B
        orig = B._greedy_find_bin_native
        B._greedy_find_bin_native = lambda *a: None
        try:
            py = greedy_find_bin(dv, ct, mb, total, mdib)
        finally:
            B._greedy_find_bin_native = orig
        np.testing.assert_array_equal(np.asarray(nat), np.asarray(py),
                                      err_msg=f"trial {trial}")
