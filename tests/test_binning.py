"""BinMapper semantics tests (reference: src/io/bin.cpp FindBin)."""

import numpy as np
import pytest

from lightgbm_tpu.binning import BinMapper, BinType, MissingType


def test_simple_uniform_binning():
    rng = np.random.RandomState(0)
    vals = rng.rand(1000) + 0.5  # all positive, no zeros
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=1000, max_bin=16, min_data_in_bin=3)
    assert 2 <= m.num_bin <= 16
    assert m.missing_type == MissingType.NONE
    bins = m.value_to_bin(vals)
    assert bins.min() >= 0 and bins.max() < m.num_bin
    # monotone: larger values -> same or larger bin
    order = np.argsort(vals)
    assert (np.diff(bins[order]) >= 0).all()


def test_upper_bounds_are_inclusive():
    m = BinMapper()
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0] * 10)
    m.find_bin(vals, total_sample_cnt=50, max_bin=5, min_data_in_bin=1)
    for b in range(m.num_bin - 1):
        ub = m.bin_upper_bound[b]
        if np.isfinite(ub):
            assert m.value_to_bin(np.array([ub]))[0] == b
            assert m.value_to_bin(np.array([np.nextafter(ub, np.inf)]))[0] == b + 1


def test_zero_bin_and_negative():
    vals = np.array([-2.0, -1.0, 1.0, 2.0] * 25)
    # 100 stored values of 200 rows -> 100 implicit zeros
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=200, max_bin=10, min_data_in_bin=1)
    zb = m.value_to_bin(np.array([0.0]))[0]
    assert m.default_bin == zb
    assert m.value_to_bin(np.array([-1.0]))[0] < zb
    assert m.value_to_bin(np.array([1.0]))[0] > zb


def test_nan_missing_type():
    vals = np.array([1.0, 2.0, 3.0, np.nan, np.nan] * 20)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=100, max_bin=10, min_data_in_bin=1)
    assert m.missing_type == MissingType.NAN
    assert m.value_to_bin(np.array([np.nan]))[0] == m.num_bin - 1


def test_zero_as_missing():
    vals = np.array([1.0, 2.0, 3.0, 4.0] * 20)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=200, max_bin=10, min_data_in_bin=1,
               zero_as_missing=True)
    assert m.missing_type == MissingType.ZERO


def test_trivial_feature():
    vals = np.ones(0)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=100, max_bin=10, min_data_in_bin=1)
    assert m.is_trivial


def test_categorical_count_sort():
    # category 3 most frequent, then 1, then 7
    vals = np.array([3.0] * 50 + [1.0] * 30 + [7.0] * 20)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=100, max_bin=10, min_data_in_bin=1,
               bin_type=BinType.CATEGORICAL)
    assert m.bin_type == BinType.CATEGORICAL
    assert m.bin_2_categorical[0] == 3
    assert m.value_to_bin(np.array([3.0]))[0] == 0
    assert m.value_to_bin(np.array([1.0]))[0] == 1
    assert m.value_to_bin(np.array([7.0]))[0] == 2
    # unseen category -> last bin
    assert m.value_to_bin(np.array([99.0]))[0] == m.num_bin - 1


def test_categorical_zero_not_first_bin():
    vals = np.array([0.0] * 50 + [1.0] * 30)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=80, max_bin=10, min_data_in_bin=1,
               bin_type=BinType.CATEGORICAL)
    # reference avoids bin0 == category 0 (bin.cpp:459-466)
    assert m.bin_2_categorical[0] != 0


def test_min_data_in_bin_respected():
    vals = np.concatenate([np.full(5, i, float) for i in range(1, 21)])
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=100, max_bin=100, min_data_in_bin=10)
    # 20 distinct values x5 rows with min 10/bin -> bins hold >= 2 values
    assert m.num_bin <= 11


def test_serialization_roundtrip():
    rng = np.random.RandomState(1)
    vals = np.concatenate([rng.randn(500), [np.nan] * 20])
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=600, max_bin=32, min_data_in_bin=3)
    m2 = BinMapper.from_dict(m.to_dict())
    test_vals = np.concatenate([rng.randn(100), [np.nan, 0.0]])
    np.testing.assert_array_equal(m.value_to_bin(test_vals), m2.value_to_bin(test_vals))


def test_efb_bundling_exact_parity():
    """Mutually-exclusive one-hot features bundle into few columns and give
    IDENTICAL models to enable_bundle=false (zero conflicts -> EFB exact).
    reference: Dataset::FindGroups / FastFeatureBundling (dataset.cpp:97-313).
    """
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    n = 3000
    cat = rng.randint(0, 12, n)
    onehot = np.zeros((n, 12), np.float64)
    onehot[np.arange(n), cat] = 1.0
    dense = rng.randn(n, 3)
    X = np.column_stack([onehot, dense])
    y = ((cat % 3 == 0) * 1.0 + 0.4 * dense[:, 0] + 0.2 * rng.randn(n) > 0.5)

    ds_on = lgb.Dataset(X, label=y.astype(np.float64),
                        params={"min_data_in_leaf": 5})
    ds_on.construct()
    ds_off = lgb.Dataset(X, label=y.astype(np.float64),
                         params={"enable_bundle": False,
                                 "min_data_in_leaf": 5})
    ds_off.construct()
    # the 12 one-hot columns must share a handful of merged columns
    assert ds_on.num_groups < ds_off.num_groups == len(ds_off.used_features)
    assert ds_on.binned.shape[1] == ds_on.num_groups

    params = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
              "min_data_in_leaf": 5}
    b_on = lgb.train(params, ds_on, num_boost_round=8, verbose_eval=False)
    b_off = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y.astype(np.float64),
                                  params={"enable_bundle": False,
                                          "min_data_in_leaf": 5}),
                      num_boost_round=8, verbose_eval=False)
    # bundled histograms reconstruct the shared default bin from f32 leaf
    # totals (FixHistogram), so gains match only to float precision; the
    # FIRST tree must still pick identical splits, and model quality match.
    t_on, t_off = b_on.boosting.models[0], b_off.boosting.models[0]
    np.testing.assert_array_equal(t_on.split_feature, t_off.split_feature)
    np.testing.assert_allclose(t_on.threshold, t_off.threshold, rtol=1e-6)
    p_on = b_on.predict(X)
    p_off = b_off.predict(X)
    from sklearn.metrics import log_loss
    assert abs(log_loss(y, p_on) - log_loss(y, p_off)) < 1e-3


def test_efb_binary_cache_roundtrip(tmp_path):
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(1)
    n = 500
    onehot = np.eye(8)[rng.randint(0, 8, n)]
    X = np.column_stack([onehot, rng.randn(n, 2)])
    y = (onehot[:, 0] + rng.randn(n) * 0.1 > 0.5).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5})
    ds.construct()
    path = str(tmp_path / "efb.bin")
    ds.save_binary(path)
    ds2 = lgb.Dataset.load_binary(path)
    np.testing.assert_array_equal(ds.binned, ds2.binned)
    np.testing.assert_array_equal(ds.feat_group, ds2.feat_group)
    np.testing.assert_array_equal(ds.feat_start, ds2.feat_start)
    bst = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 7,
                     "min_data_in_leaf": 5}, ds2, num_boost_round=3,
                    verbose_eval=False)
    assert bst.num_trees() == 3
