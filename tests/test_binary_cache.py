"""Binary dataset cache semantics.

reference: Dataset::SaveBinaryFile (dataset.cpp:890) / DatasetLoader::
LoadFromBinFile (dataset_loader.cpp:273) — a file whose header carries the
binary token routes to the binary loader whatever its name, stored
construction params drive param-change checking, and a cache used as a
validation set must share the training set's bin mappers.  Plus
Common::AvoidInf metadata sanitization (utils/common.h:697).
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import LightGBMError


@pytest.fixture()
def problem():
    rng = np.random.RandomState(0)
    X = rng.rand(600, 5)
    y = (X[:, 0] * 3 + 0.01 * rng.randn(600)).astype(np.float64)
    return X, y


def test_construct_routes_binary_by_magic(tmp_path, problem):
    X, y = problem
    p = str(tmp_path / "cache.weird_extension")
    ds = lgb.Dataset(X, y, params={"max_bin": 63})
    ds.construct()
    ds.save_binary(p)
    loaded = lgb.Dataset(p)
    loaded.construct()
    assert loaded.num_data == len(X)
    assert loaded.params.get("max_bin") == 63      # file params restored
    np.testing.assert_allclose(loaded.get_label(), y.astype(np.float32))
    # subset of a file-backed dataset (reference test_engine
    # test_init_with_subset flow)
    sub = lgb.Dataset(p).subset(np.arange(100))
    sub.construct()
    assert sub.num_data == 100


def test_binary_cache_param_conflicts(tmp_path, problem):
    X, y = problem
    p = str(tmp_path / "t.bin")
    ds = lgb.Dataset(X, y, params={"max_bin": 63, "min_data_in_leaf": 20})
    ds.construct()
    ds.save_binary(p)
    # growing min_data_in_leaf is allowed (training-time constraint)
    lgb.train({"objective": "regression", "min_data_in_leaf": 50,
               "verbose": -1}, lgb.Dataset(p), num_boost_round=2)
    # changing a binning param is not (no raw data to rebuild from)
    with pytest.raises(LightGBMError, match="Cannot change max_bin"):
        lgb.train({"objective": "regression", "max_bin": 128,
                   "verbose": -1}, lgb.Dataset(p), num_boost_round=1)


def test_binary_cache_valid_set_mapper_alignment(tmp_path, problem):
    X, y = problem
    rng = np.random.RandomState(7)
    tr = lgb.Dataset(X, y)
    tr.construct()
    # aligned: valid cache binned against the training set's mappers
    pv = str(tmp_path / "v.bin")
    vd = lgb.Dataset(X[:200], y[:200], reference=tr)
    vd.construct()
    vd.save_binary(pv)
    ev = {}
    lgb.train({"objective": "regression", "verbose": -1}, tr,
              num_boost_round=2, valid_sets=[lgb.Dataset(pv, reference=tr)],
              evals_result=ev, verbose_eval=False)
    assert "valid_0" in ev
    # misaligned: cache binned standalone on a different distribution
    pv2 = str(tmp_path / "v2.bin")
    sd = lgb.Dataset(rng.rand(300, 5) * 2.0, y[:300])
    sd.construct()
    sd.save_binary(pv2)
    with pytest.raises(LightGBMError, match="different bin mappers"):
        lgb.train({"objective": "regression", "verbose": -1}, tr,
                  num_boost_round=1,
                  valid_sets=[lgb.Dataset(pv2, reference=tr)],
                  verbose_eval=False)


def test_metadata_avoid_inf(problem):
    X, y = problem
    seq = np.ones(len(y))
    seq[0] = np.nan
    seq[1] = np.inf
    d = lgb.Dataset(X, seq, weight=seq, init_score=seq).construct()
    assert d.label[0] == 0.0 and not np.isinf(d.label[1])
    assert d.weight[0] == 0.0 and not np.isinf(d.weight[1])
    assert d.init_score[0] == 0.0 and not np.isinf(d.init_score[1])
    assert d.label[1] == d.weight[1]
    # setters sanitize too
    d2 = lgb.Dataset(X, y).construct()
    d2.set_label(seq)
    d2.set_weight(seq)
    d2.set_init_score(seq)
    assert not np.isnan(d2.label[0])
    assert not np.isinf(d2.weight[1])
    assert not np.isinf(d2.init_score[1])
