"""Batched-frontier grower (grower_rounds.py) vs serial grower equality.

The rounds grower must produce STRUCTURALLY IDENTICAL trees to the serial
best-first grower — same splits, same node/leaf numbering — for every gain
pattern (its exactness check falls back to single steps when a round would
deviate).  Float fields (gains, sums, leaf values) agree only to float32
accumulation order: the two growers sum histogram bins in different orders,
the same class of difference as the reference's CPU vs GPU histograms.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.dataset import FeatureMeta
from lightgbm_tpu.parallel.learners import shard_map_compat
from lightgbm_tpu.grower import GrowerConfig, grow_tree
from lightgbm_tpu.grower_rounds import grow_tree_rounds
from lightgbm_tpu.ops.split import SplitHyperparams


def _meta(B, F):
    return FeatureMeta(
        num_bin=np.full(F, B, np.int32),
        missing_type=np.zeros(F, np.int32),
        default_bin=np.zeros(F, np.int32),
        most_freq_bin=np.zeros(F, np.int32),
        is_categorical=np.zeros(F, bool),
        max_num_bin=B,
    )


def _assert_trees_equal(t1, t2):
    nl = int(t1.num_leaves)
    assert nl == int(t2.num_leaves)
    nn = max(nl - 1, 1)
    for name in ("split_feature", "threshold_bin", "default_left",
                 "is_categorical", "left_child", "right_child"):
        np.testing.assert_array_equal(
            np.asarray(getattr(t1, name))[:nn],
            np.asarray(getattr(t2, name))[:nn], err_msg=name)
    for name in ("split_gain", "internal_value", "internal_count"):
        np.testing.assert_allclose(
            np.asarray(getattr(t1, name))[:nn],
            np.asarray(getattr(t2, name))[:nn], rtol=3e-5, err_msg=name)
    for name in ("leaf_value", "leaf_weight", "leaf_count"):
        np.testing.assert_allclose(
            np.asarray(getattr(t1, name))[:nl],
            np.asarray(getattr(t2, name))[:nl], rtol=3e-5, atol=1e-7,
            err_msg=name)


def _grow_both(binned, grad, hess, mask, meta, cfg, mc=None):
    t_s, lid_s = grow_tree(jnp.asarray(binned.T), jnp.asarray(grad),
                           jnp.asarray(hess), jnp.asarray(mask), meta, cfg,
                           monotone_constraints=mc)
    t_r, lid_r = grow_tree_rounds(jnp.asarray(binned.T), jnp.asarray(grad),
                                  jnp.asarray(hess), jnp.asarray(mask),
                                  meta, cfg, monotone_constraints=mc)
    _assert_trees_equal(t_s, t_r)
    np.testing.assert_array_equal(np.asarray(lid_s), np.asarray(lid_r))


@pytest.fixture
def problem():
    rng = np.random.RandomState(7)
    n, F, B = 4096, 10, 32
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    grad = (rng.randn(n) + 0.7 * (binned[:, 1] > 16)
            - 0.4 * (binned[:, 3] < 5)).astype(np.float32)
    hess = np.ones(n, np.float32)
    return binned, grad, hess, B, F


@pytest.mark.parametrize("leaves", [2, 7, 31, 64])
def test_rounds_equals_serial(problem, leaves):
    binned, grad, hess, B, F = problem
    cfg = GrowerConfig(num_leaves=leaves, num_bins=B, hp=SplitHyperparams(),
                       hist_method="scatter")
    _grow_both(binned, grad, hess, np.ones(len(grad), np.float32),
               _meta(B, F), cfg)


def test_rounds_equals_serial_bagging_and_depth(problem):
    binned, grad, hess, B, F = problem
    rng = np.random.RandomState(3)
    mask = (rng.rand(len(grad)) < 0.7).astype(np.float32) * 2.0
    cfg = GrowerConfig(num_leaves=31, max_depth=4, num_bins=B,
                       hp=SplitHyperparams(min_data_in_leaf=40),
                       hist_method="scatter")
    _grow_both(binned, grad, hess, mask, _meta(B, F), cfg)


def test_rounds_equals_serial_monotone(problem):
    binned, grad, hess, B, F = problem
    mc = np.zeros(F, np.int32)
    mc[1] = 1
    mc[3] = -1
    cfg = GrowerConfig(num_leaves=31, num_bins=B, hp=SplitHyperparams(),
                       hist_method="scatter")
    _grow_both(binned, grad, hess, np.ones(len(grad), np.float32),
               _meta(B, F), cfg, mc=jnp.asarray(mc))


def test_rounds_equals_serial_adversarial_xor():
    """XOR-style data: a child's split gain EXCEEDS its parent's, forcing
    the rounds grower through its exactness fallback path."""
    rng = np.random.RandomState(0)
    n, F, B = 4096, 6, 16
    binned = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    a = binned[:, 0] >= 8
    b = binned[:, 1] >= 8
    grad = (np.where(a ^ b, 1.0, -1.0) + 0.01 * rng.randn(n)
            ).astype(np.float32)
    hess = np.ones(n, np.float32)
    for leaves in (4, 9, 31):
        cfg = GrowerConfig(num_leaves=leaves, num_bins=B,
                           hp=SplitHyperparams(), hist_method="scatter")
        _grow_both(binned, grad, hess, np.ones(n, np.float32),
                   _meta(B, F), cfg)


def test_rounds_equals_serial_extra_trees_and_bynode(problem):
    """Node RNG keys derive from node identity in both growers, so the
    randomized modes stay structurally identical too."""
    import jax
    binned, grad, hess, B, F = problem
    cfg = GrowerConfig(num_leaves=31, num_bins=B,
                       hp=SplitHyperparams(extra_trees=True),
                       bynode_feature_cnt=5, hist_method="scatter")
    mask = np.ones(len(grad), np.float32)
    meta = _meta(B, F)
    key = jax.random.PRNGKey(42)
    t_s, lid_s = grow_tree(jnp.asarray(binned.T), jnp.asarray(grad),
                           jnp.asarray(hess), jnp.asarray(mask), meta, cfg,
                           rng_key=key)
    t_r, lid_r = grow_tree_rounds(jnp.asarray(binned.T), jnp.asarray(grad),
                                  jnp.asarray(hess), jnp.asarray(mask),
                                  meta, cfg, rng_key=key)
    _assert_trees_equal(t_s, t_r)
    np.testing.assert_array_equal(np.asarray(lid_s), np.asarray(lid_r))


def test_rounds_data_parallel_matches_single(problem):
    """Rounds grower under shard_map row sharding == single-device rounds."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    binned, grad, hess, B, F = problem
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=15, num_bins=B,
                       hp=SplitHyperparams(min_data_in_leaf=10),
                       hist_method="scatter")
    mask = np.ones(len(grad), np.float32)
    ref_tree, ref_leaf = grow_tree_rounds(
        jnp.asarray(binned.T), jnp.asarray(grad), jnp.asarray(hess),
        jnp.asarray(mask), meta, cfg)

    assert jax.device_count() >= 8
    mesh = Mesh(np.array(jax.devices()[:8]), ("d",))
    sharded = shard_map_compat(
        lambda b, g, h, m: grow_tree_rounds(b, g, h, m, meta, cfg,
                                            axis_name="d"),
        mesh=mesh, in_specs=(P(None, "d"), P("d"), P("d"), P("d")),
        out_specs=(P(), P("d")), check_vma=False)
    tree, leaf_id = jax.jit(sharded)(
        np.ascontiguousarray(binned.T), grad, hess, mask)

    nl = int(ref_tree.num_leaves)
    assert int(tree.num_leaves) == nl
    np.testing.assert_array_equal(np.asarray(tree.split_feature[:nl - 1]),
                                  np.asarray(ref_tree.split_feature[:nl - 1]))
    np.testing.assert_array_equal(np.asarray(tree.threshold_bin[:nl - 1]),
                                  np.asarray(ref_tree.threshold_bin[:nl - 1]))
    np.testing.assert_allclose(np.asarray(tree.leaf_value[:nl]),
                               np.asarray(ref_tree.leaf_value[:nl]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(leaf_id),
                                  np.asarray(ref_leaf))


def test_fast_mode_trains_equivalent_quality():
    """tpu_tree_growth=fast (no exactness fallback) may pick a different
    final-level split set, but trained quality must match exact growth."""
    rng = np.random.RandomState(2)
    n = 6000
    X = rng.rand(n, 10).astype(np.float32)
    y = ((X[:, 0] + X[:, 1] * X[:, 2] - X[:, 3] + 0.2 * rng.randn(n)) > 0.2
         ).astype(np.float32)
    Xt, yt = X[:4500], y[:4500]
    Xv, yv = X[4500:], y[4500:]
    loss = {}
    for mode in ("rounds", "fast"):
        params = {"objective": "binary", "num_leaves": 31, "max_bin": 32,
                  "metric": "binary_logloss", "verbosity": -1,
                  "tpu_tree_growth": mode}
        ds = lgb.Dataset(Xt, label=yt)
        evals = {}
        import lightgbm_tpu.callback as cb
        bst = lgb.train(params, ds, num_boost_round=10,
                        valid_sets=[ds.create_valid(Xv, label=yv)],
                        valid_names=["v"],
                        callbacks=[lgb.record_evaluation(evals)])
        assert bst.models[0].num_leaves == 31
        loss[mode] = evals["v"]["binary_logloss"][-1]
    assert abs(loss["fast"] - loss["rounds"]) < 0.01, loss


def test_rounds_engine_matches_serial_model():
    """End-to-end through the engine (incl. EFB bundling and multiple
    boosting iterations): same structures, predictions within float
    accumulation tolerance."""
    rng = np.random.RandomState(11)
    n = 3000
    X = rng.rand(n, 12).astype(np.float32)
    X[:, 5] = (X[:, 5] > 0.6).astype(np.float32)     # sparse-ish for EFB
    X[:, 7] = 0.0
    y = ((X[:, 0] + X[:, 1] * X[:, 2] - X[:, 5] + 0.2 * rng.randn(n)) > 0.5
         ).astype(np.float32)
    dumps, preds = {}, {}
    for mode in ("serial", "rounds"):
        params = {"objective": "binary", "num_leaves": 15,
                  "min_data_in_leaf": 20, "max_bin": 32, "verbosity": -1,
                  "tpu_tree_growth": mode}
        ds = lgb.Dataset(X, label=y)
        booster = lgb.train(params, ds, num_boost_round=8)
        dumps[mode] = booster.dump_model()
        preds[mode] = booster.predict(X)

    def structures(d):
        out = []
        def walk(node):
            if "split_feature" in node:
                out.append((node["split_feature"], node["threshold"],
                            node["default_left"]))
                walk(node["left_child"]); walk(node["right_child"])
        for t in d["tree_info"]:
            walk(t["tree_structure"])
        return out

    assert structures(dumps["serial"]) == structures(dumps["rounds"])
    np.testing.assert_allclose(preds["serial"], preds["rounds"],
                               rtol=2e-4, atol=2e-6)


def test_rounds_goss_matches_serial():
    """GOSS amplified weights flow through the rounds grower's weighted
    smaller-child selection identically to serial growth."""
    rng = np.random.RandomState(4)
    n = 5000
    X = rng.rand(n, 8).astype(np.float32)
    y = ((X[:, 0] * X[:, 1] + 0.1 * rng.randn(n)) > 0.25).astype(np.float32)
    preds = {}
    for mode in ("serial", "rounds"):
        params = {"objective": "binary", "boosting": "goss",
                  "top_rate": 0.3, "other_rate": 0.2, "num_leaves": 15,
                  "max_bin": 32, "verbosity": -1, "tpu_tree_growth": mode,
                  "learning_rate": 0.2}
        bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=12)
        preds[mode] = bst.predict(X)
    np.testing.assert_allclose(preds["serial"], preds["rounds"],
                               rtol=2e-4, atol=2e-6)


def test_rounds_equals_serial_categorical():
    """Categorical splits (one-hot + sorted many-vs-many bitsets) through
    the batched partition's per-row bitset path."""
    rng = np.random.RandomState(9)
    n = 5000
    Xnum = rng.rand(n, 4).astype(np.float32)
    cat1 = rng.randint(0, 12, n)
    cat2 = rng.randint(0, 5, n)
    X = np.column_stack([Xnum, cat1, cat2]).astype(np.float32)
    eff = np.array([0.9, -0.4, 0.1, 0.6, -0.8, 0.2, 0.5, -0.3, 0.0, 0.7,
                    -0.6, 0.4])
    y = ((X[:, 0] + eff[cat1] + 0.3 * (cat2 == 2) + 0.15 * rng.randn(n))
         > 0.5).astype(np.float32)
    dumps, preds = {}, {}
    for mode in ("serial", "rounds"):
        params = {"objective": "binary", "num_leaves": 15, "max_bin": 32,
                  "verbosity": -1, "tpu_tree_growth": mode,
                  "categorical_feature": [4, 5],
                  "min_data_per_group": 10, "cat_smooth": 5.0}
        bst = lgb.train(params, lgb.Dataset(
            X, label=y, categorical_feature=[4, 5]), num_boost_round=6)
        dumps[mode] = bst.dump_model()
        preds[mode] = bst.predict(X)

    def structures(d):
        out = []
        def walk(nd):
            if "split_feature" in nd:
                out.append((nd["split_feature"], nd.get("threshold"),
                            nd.get("decision_type")))
                walk(nd["left_child"]); walk(nd["right_child"])
        for t in d["tree_info"]:
            walk(t["tree_structure"])
        return out

    assert structures(dumps["serial"]) == structures(dumps["rounds"])
    np.testing.assert_allclose(preds["serial"], preds["rounds"],
                               rtol=2e-4, atol=2e-6)


def test_rounds_equals_serial_sorted_seghist(problem, monkeypatch):
    """The sorted-arena segment histogram (the TPU path) must leave the
    rounds grower structurally identical to the serial grower; forced on
    CPU via the LGBM_TPU_SEGHIST testing hook."""
    monkeypatch.setenv("LGBM_TPU_SEGHIST", "sorted")
    binned, grad, hess, B, F = problem
    mask = np.ones(len(grad), np.float32)
    meta = _meta(B, F)
    for leaves in (7, 31, 64):
        cfg = GrowerConfig(num_leaves=leaves, num_bins=B,
                           hp=SplitHyperparams(), hist_method="scatter")
        t_s, lid_s = grow_tree(jnp.asarray(binned.T), jnp.asarray(grad),
                               jnp.asarray(hess), jnp.asarray(mask),
                               meta, cfg)
        t_r, lid_r = grow_tree_rounds(jnp.asarray(binned.T), jnp.asarray(grad),
                                      jnp.asarray(hess), jnp.asarray(mask),
                                      meta, cfg)
        # structure must be identical; floats only to accumulation order
        # (the sorted arena reduces via block partials — one more stage of
        # f32 reordering than the scatter path, hence the looser rtol)
        nl = int(t_s.num_leaves)
        assert nl == int(t_r.num_leaves)
        nn = max(nl - 1, 1)
        for name in ("split_feature", "threshold_bin", "default_left",
                     "left_child", "right_child"):
            np.testing.assert_array_equal(
                np.asarray(getattr(t_s, name))[:nn],
                np.asarray(getattr(t_r, name))[:nn], err_msg=name)
        np.testing.assert_array_equal(np.asarray(lid_s), np.asarray(lid_r))
        for name in ("leaf_value", "split_gain"):
            np.testing.assert_allclose(
                np.asarray(getattr(t_s, name))[:nn],
                np.asarray(getattr(t_r, name))[:nn], rtol=2e-4, atol=1e-5,
                err_msg=name)


def test_rounds_data_parallel_sorted_dispatch(problem, monkeypatch):
    """The TPU seghist dispatch (slot-expanded pass / sorted arena, forced
    via LGBM_TPU_SEGHIST=sorted) must agree with single-device growth when
    psum'd under shard_map row sharding — the headline TPU configuration."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    monkeypatch.setenv("LGBM_TPU_SEGHIST", "sorted")
    binned, grad, hess, B, F = problem
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=15, num_bins=B,
                       hp=SplitHyperparams(min_data_in_leaf=10),
                       hist_method="matmul_f32")
    mask = np.ones(len(grad), np.float32)
    ref_tree, ref_leaf = grow_tree_rounds(
        jnp.asarray(binned.T), jnp.asarray(grad), jnp.asarray(hess),
        jnp.asarray(mask), meta, cfg)

    mesh = Mesh(np.array(jax.devices()[:8]), ("d",))
    sharded = shard_map_compat(
        lambda b, g, h, m: grow_tree_rounds(b, g, h, m, meta, cfg,
                                            axis_name="d"),
        mesh=mesh, in_specs=(P(None, "d"), P("d"), P("d"), P("d")),
        out_specs=(P(), P("d")), check_vma=False)
    tree, leaf_id = jax.jit(sharded)(
        np.ascontiguousarray(binned.T), grad, hess, mask)

    nl = int(ref_tree.num_leaves)
    assert int(tree.num_leaves) == nl
    np.testing.assert_array_equal(np.asarray(tree.split_feature[:nl - 1]),
                                  np.asarray(ref_tree.split_feature[:nl - 1]))
    np.testing.assert_allclose(np.asarray(tree.leaf_value[:nl]),
                               np.asarray(ref_tree.leaf_value[:nl]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(leaf_id), np.asarray(ref_leaf))


def test_router_matmul_matches_scan(problem, monkeypatch):
    """The router-matmul candidate routing (one-hot table lookup +
    select-reduce bin read) must produce the identical tree to the
    candidate scan it replaces."""
    binned, grad, hess, B, F = problem
    meta = _meta(B, F)
    cfg = GrowerConfig(num_leaves=31, num_bins=B,
                       hp=SplitHyperparams(min_data_in_leaf=10),
                       hist_method="matmul_f32")
    mask = np.ones(len(grad), np.float32)
    monkeypatch.setenv("LGBM_TPU_SEGHIST", "sorted")
    monkeypatch.setenv("LGBM_TPU_ROUTER", "0")
    t_scan, lid_scan = grow_tree_rounds(
        jnp.asarray(binned.T), jnp.asarray(grad), jnp.asarray(hess),
        jnp.asarray(mask), meta, cfg)
    monkeypatch.setenv("LGBM_TPU_ROUTER", "1")
    t_rt, lid_rt = grow_tree_rounds(
        jnp.asarray(binned.T), jnp.asarray(grad), jnp.asarray(hess),
        jnp.asarray(mask), meta, cfg)
    _assert_trees_equal(t_scan, t_rt)
    np.testing.assert_array_equal(np.asarray(lid_scan), np.asarray(lid_rt))
