"""Deferred host-tree materialization (round 5).

On the tunneled accelerator backend every device->host copy costs a ~70 ms
network round-trip, so GBDT._finish_iter banks stacked DEVICE trees and
converts the backlog in ONE bulk transfer when the host model list is
actually needed (GBDT._drain_pending).  These tests force the deferred path
on the CPU backend (LGBT_DEFER_HOST_TREES=1) and pin down that it is
bit-identical to the eager path — models, predictions, stop semantics,
rollback, and iteration-0 init-score bias.
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture()
def defer_env():
    os.environ["LGBT_DEFER_HOST_TREES"] = "1"
    yield
    os.environ.pop("LGBT_DEFER_HOST_TREES", None)


def _data(n=2000, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] + 0.1 * rng.randn(n) > 1.0).astype(
        np.float32)
    return X, y


def _fit(X, y, params, rounds, defer):
    os.environ["LGBT_DEFER_HOST_TREES"] = "1" if defer else "0"
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params=params, train_set=ds)
    for _ in range(rounds):
        if bst.update():
            break
    return bst


def test_deferred_matches_eager_bitwise(defer_env):
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
              "verbosity": -1, "bagging_fraction": 0.8, "bagging_freq": 1,
              "feature_fraction": 0.9}
    b0 = _fit(X, y, params, 30, defer=False)
    b1 = _fit(X, y, params, 30, defer=True)
    assert b1.num_trees() == b0.num_trees() == 30
    assert np.array_equal(b0.predict(X), b1.predict(X))
    assert b0.model_to_string() == b1.model_to_string()


def test_deferred_stop_truncates_like_eager(defer_env):
    # nothing splittable: reference stops with the iteration-0 constant
    # tree kept (gbdt.cpp:387-405); the deferred drain truncates to match
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 7,
              "min_data_in_leaf": 5000, "verbosity": -1}
    b0 = _fit(X, y, params, 5, defer=False)
    b1 = _fit(X, y, params, 5, defer=True)
    assert b1.num_trees() == b0.num_trees() == 1
    assert b1.boosting.iter == b0.boosting.iter == 0
    assert np.allclose(b0.predict(X), b1.predict(X))


def test_deferred_rollback_and_continue(defer_env):
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    b0 = _fit(X, y, params, 6, defer=False)
    b0.rollback_one_iter()

    os.environ["LGBT_DEFER_HOST_TREES"] = "1"
    ds = lgb.Dataset(X, label=y, params=params)
    b1 = lgb.Booster(params=params, train_set=ds)
    for _ in range(6):
        b1.update()
    b1.rollback_one_iter()   # drains, then trims the host list
    assert b1.num_trees() == b0.num_trees() == 5
    assert np.array_equal(b0.predict(X), b1.predict(X))
    b1.update()              # deferral resumes after a drain
    assert b1.num_trees() == 6


def test_deferred_init_score_bias(defer_env):
    X, y = _data()
    init = np.full(len(y), 0.7, np.float32)
    params = {"objective": "regression", "num_leaves": 7, "verbosity": -1}

    def fit(defer):
        os.environ["LGBT_DEFER_HOST_TREES"] = "1" if defer else "0"
        ds = lgb.Dataset(X, label=y, params=params,
                         init_score=init)
        bst = lgb.Booster(params=params, train_set=ds)
        for _ in range(3):
            bst.update()
        return bst

    b0, b1 = fit(False), fit(True)
    assert b0.model_to_string() == b1.model_to_string()


def test_deferred_eval_during_training(defer_env):
    # eval_valid reads device scores, not host trees: per-iteration eval
    # must not force a drain (pending backlog survives)
    X, y = _data()
    Xv, yv = _data(seed=1)
    params = {"objective": "binary", "metric": "auc", "num_leaves": 15,
              "verbosity": -1}
    os.environ["LGBT_DEFER_HOST_TREES"] = "1"
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params=params, train_set=ds)
    bst.add_valid(lgb.Dataset(Xv, label=yv, params=params, reference=ds),
                  "v0")
    aucs = []
    for _ in range(5):
        bst.update()
        aucs.append(bst.eval_valid()[0][2])
    assert len(bst.boosting._pending) == 5      # nothing drained yet
    assert aucs[-1] > aucs[0]
    assert bst.num_trees() == 5                 # drain on demand
    assert len(bst.boosting._pending) == 0
