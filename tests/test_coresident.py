"""Co-resident train+serve (lightgbm_tpu/coresident/ +
ops/planner.py ResidencyLedger): one pod, whole lifecycle.

The load-bearing claims:

* every planner entry point leases from ONE per-device budget, so the
  combined train+serve peak never exceeds it, tile size degrades before
  serving residency, and an infeasible co-residency is a LOUD verdict
  (``CoresidencyInfeasible`` carrying the lease table), never an OOM;
* the engine's ``pause_control`` seam evicts full training state to a
  checkpoint bundle and the paused+resumed refresh produces a model
  BYTE-identical to the uninterrupted one;
* brownout breaches throttle, then pause, then resume training through
  the watchdog's windowed-p99 breach stream — and a refresh paused by
  brownout does not storm the ``freshness:`` SLO (single rising-edge
  dump, monotonic age gauge);
* losing a device mid-co-residency drains the serving replicas AND
  shrinks the training world in one coordinated replan, with a
  ``coresident:device_lost`` flight bundle naming both planes.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.coresident import (CoresidencyInfeasible,
                                     CoresidentConfig, PauseControl,
                                     Scheduler)
from lightgbm_tpu.engine import TrainingPaused
from lightgbm_tpu.fleet import PodFleet
from lightgbm_tpu.obs.flight import global_flight
from lightgbm_tpu.obs.metrics import MetricsRegistry, global_registry
from lightgbm_tpu.obs.watchdog import Watchdog, global_watchdog
from lightgbm_tpu.ops.planner import (HEADROOM, FleetModelShape,
                                      LedgerError, ResidencyLedger,
                                      active_ledger, plan_fleet,
                                      plan_histograms, set_active_ledger)
from lightgbm_tpu.resilience.faults import ChaosRegistry, FaultSpec

pytestmark = pytest.mark.coresident

F = 8


@pytest.fixture(autouse=True)
def _flight_tmp(tmp_path, monkeypatch):
    """Own flight dir + fresh dump budget per test (breach dumps are the
    point here; the process cap must not starve later tests)."""
    monkeypatch.setattr(global_flight, "_out_dir", str(tmp_path))
    monkeypatch.setattr(global_flight, "dumps", 0)
    monkeypatch.setattr(global_flight, "max_dumps", 1 << 20)
    yield


def _data(seed, n, f=F):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32).astype(np.float64)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    return X, y


def _dumps(sub=""):
    # dump filenames sanitize the trigger (":" -> "_"), match likewise
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in sub)
    try:
        return [d for d in os.listdir(global_flight.out_dir())
                if d.startswith("flight_") and safe in d]
    except OSError:
        return []


PARAMS = {"objective": "binary", "verbosity": -1, "num_leaves": 15}


# ======================================================== ResidencyLedger


def test_ledger_lease_release_accounting():
    lg = ResidencyLedger(limit_bytes=1_000_000)
    assert lg.budget_bytes == int(1_000_000 * HEADROOM)
    assert lg.available_bytes() == lg.budget_bytes
    a = lg.lease("serve:m", 300_000, plane="serving")
    b = lg.lease("refresh:m", 200_000, plane="train", preemptible=True)
    assert lg.leased_bytes() == 500_000
    assert lg.leased_bytes(plane="serving") == 300_000
    assert lg.available_bytes() == lg.budget_bytes - 500_000
    s = lg.summary()
    assert s["num_leases"] == 2
    assert s["leased_by_plane"] == {"serving": 300_000, "train": 200_000}
    lg.release(b)
    lg.release(b)                       # idempotent
    assert lg.leased_bytes() == 300_000
    lg.release(a.lease_id)              # release by id too
    assert lg.leased_bytes() == 0
    assert not lg.table()


def test_ledger_denial_is_loud_with_lease_table():
    lg = ResidencyLedger(limit_bytes=1_000_000)
    lg.lease("serve:hot", 700_000, plane="serving")
    with pytest.raises(LedgerError) as ei:
        lg.lease("refresh:big", 500_000, plane="train")
    msg = str(ei.value)
    assert "serve:hot" in msg           # the lease table names the holder
    assert "refresh:big" in msg
    assert lg.try_lease("refresh:big", 500_000, plane="train") is None
    # the denial did not corrupt accounting
    assert lg.leased_bytes() == 700_000


def test_ledger_preempt_evicts_only_preemptible():
    lg = ResidencyLedger(limit_bytes=1_000_000)
    lg.lease("serve:m", 300_000, plane="serving", preemptible=False)
    lg.lease("refresh:a", 100_000, plane="train", preemptible=True)
    lg.lease("refresh:b", 150_000, plane="train", preemptible=True)
    freed = lg.preempt(plane="train")
    assert freed == 250_000
    assert lg.leased_bytes() == 300_000
    assert [e["owner"] for e in lg.table()] == ["serve:m"]


def test_ledger_gauges_published():
    lg = ResidencyLedger(limit_bytes=2_000_000)
    lease = lg.lease("serve:m", 500_000, plane="serving")
    g = global_registry.to_dict()["gauges"]
    assert g["ledger_budget_bytes"] == lg.budget_bytes
    assert g["ledger_available_bytes"] == lg.available_bytes()
    leased = [v for k, v in g.items()
              if k.startswith("ledger_leased_bytes") and "serving" in k]
    assert leased and leased[0] == 500_000
    lg.release(lease)


def test_active_ledger_registration():
    prev = set_active_ledger(None)
    try:
        lg = ResidencyLedger(limit_bytes=1 << 20)
        assert set_active_ledger(lg) is None
        assert active_ledger() is lg
    finally:
        set_active_ledger(prev)


# =============================================== planners lease the budget


def test_plan_histograms_respects_ledger_remainder():
    limit = 64 << 20
    solo = plan_histograms(rows=200_000, features=28, num_bins=64,
                           num_leaves=255, budget_bytes=limit)
    assert solo.feasible
    lg = ResidencyLedger(limit_bytes=limit)
    lg.lease("serve:m", int(lg.budget_bytes * 0.7), plane="serving")
    co = plan_histograms(rows=200_000, features=28, num_bins=64,
                         num_leaves=255, ledger=lg)
    # combined peak stays inside the ONE budget: the plan fits what the
    # serving residency left over, degrading tile size — not serving
    assert co.limit_source == "ledger"
    if co.feasible:
        assert co.predicted_peak_bytes <= lg.available_bytes()
        assert co.predicted_peak_bytes + lg.leased_bytes() <= lg.budget_bytes
        assert co.tile_rows <= (solo.tile_rows or 200_000)
    # a ledger with nothing leased plans like the solo fake-budget path
    free = plan_histograms(rows=200_000, features=28, num_bins=64,
                           num_leaves=255,
                           ledger=ResidencyLedger(limit_bytes=limit))
    assert free.feasible
    assert free.tile_rows == solo.tile_rows


def test_plan_histograms_ledger_infeasible_is_verdict_not_oom():
    lg = ResidencyLedger(limit_bytes=4 << 20)
    lg.lease("serve:m", lg.budget_bytes - 1024, plane="serving")
    plan = plan_histograms(rows=5_000_000, features=28, num_bins=64,
                           num_leaves=255, ledger=lg)
    assert not plan.feasible            # refused, loudly — nothing raised


def test_plan_fleet_respects_ledger_remainder():
    limit = 32 << 20
    shapes = [FleetModelShape("hot", 400, 255, 256, F, buckets=(8, 64),
                              weight=4.0),
              FleetModelShape("cold", 400, 255, 256, F, buckets=(8, 64),
                              weight=0.1, age_s=500.0)]
    solo = plan_fleet(shapes, budget_bytes=limit)
    lg = ResidencyLedger(limit_bytes=limit)
    lg.lease("refresh:m", int(lg.budget_bytes * 0.9), plane="train")
    co = plan_fleet(shapes, ledger=lg)
    assert co.total_resident_bytes <= lg.available_bytes()
    assert co.total_resident_bytes <= solo.total_resident_bytes
    # training holding most of the device demotes residency, never serving
    assert len([m for m in co.models if m.resident]) <= \
        len([m for m in solo.models if m.resident])


def test_plan_topology_with_per_device_ledgers():
    from lightgbm_tpu.fleet.topology import plan_devices, plan_topology
    devices = plan_devices(2, budget_bytes_per_device=32 << 20)
    shapes = [FleetModelShape("m", 200, 63, 64, F, buckets=(8,))]
    lg = ResidencyLedger(limit_bytes=32 << 20)
    lg.lease("refresh:m", int(lg.budget_bytes * 0.95), plane="train")
    topo = plan_topology(shapes, devices, ledgers={0: lg})
    # device 0 plans against the ledger remainder; device 1 is untouched
    assert topo.device_plans[0].total_resident_bytes <= \
        lg.available_bytes()
    assert topo.device_plans[1].total_resident_bytes >= \
        topo.device_plans[0].total_resident_bytes


# ================================================= engine pause seam


class _PauseAt:
    """Duck-typed pause_control: run at chunk cap 1, pause at iteration
    ``at`` (None = never)."""

    def __init__(self, at):
        self.at = at
        self.consults = 0

    def consult(self, i):
        self.consults += 1
        return "pause" if self.at is not None and i >= self.at else "run"

    def chunk_cap(self):
        return 1


def test_pause_resume_bit_parity(tmp_path):
    X, y = _data(0, 1200)
    params = dict(PARAMS)

    ref = lgb.train(params, lgb.Dataset(X, label=y, free_raw_data=False),
                    10, verbose_eval=False)

    snap = str(tmp_path / "paused.txt")
    with pytest.raises(TrainingPaused) as ei:
        lgb.train(params, lgb.Dataset(X, label=y, free_raw_data=False),
                  10, verbose_eval=False, snapshot_out=snap,
                  pause_control=_PauseAt(4))
    assert ei.value.iteration == 4
    assert os.path.exists(ei.value.bundle_path)

    resumed = lgb.train(params,
                        lgb.Dataset(X, label=y, free_raw_data=False),
                        10, verbose_eval=False, snapshot_out=snap,
                        resume_from=ei.value.bundle_path,
                        pause_control=_PauseAt(None))
    assert resumed.current_iteration() == 10
    assert resumed.model_to_string() == ref.model_to_string()


def test_pause_is_not_a_failure_dump(tmp_path):
    X, y = _data(1, 800)
    snap_dir = tmp_path / "snap"       # keep bundles out of the flight dir
    snap_dir.mkdir()
    before = set(_dumps())
    with pytest.raises(TrainingPaused):
        lgb.train(dict(PARAMS),
                  lgb.Dataset(X, label=y, free_raw_data=False), 8,
                  verbose_eval=False,
                  snapshot_out=str(snap_dir / "p.txt"),
                  pause_control=_PauseAt(2))
    # an ordered yield must not produce a forensic exception bundle
    assert set(_dumps()) == before


def test_pause_control_throttle_halves_chunk_cap():
    pc = PauseControl(base_chunk_cap=16, throttle_delay_s=0.0)
    assert pc.chunk_cap() == 16
    assert pc.request_throttle()
    assert pc.state == PauseControl.THROTTLE
    assert pc.chunk_cap() == 8
    assert not pc.request_throttle()            # already throttled
    assert pc.request_pause()
    assert pc.consult(0) == "pause"
    assert not pc.request_throttle()            # pause never downgrades
    assert pc.request_run()
    assert pc.consult(1) == "run"
    assert pc.consults == 2


# ====================================== watchdog: windowed p99 + listeners


def test_windowed_p99_clears_after_brownout():
    wd = Watchdog()
    reg = MetricsRegistry()
    h = reg.histogram("lat_ms")
    wd.watch_histogram_p99("w", h, ceiling_ms=10.0, windowed=True)
    assert wd.check_once() == []                # arming sweep
    for _ in range(50):
        h.observe(200.0)
    assert any(s == "slo:w" for s, _ in wd.check_once())
    assert "slo:w" in wd.active_breaches()
    for _ in range(200):
        h.observe(1.0)                          # traffic recovered
    assert wd.check_once() == []
    assert "slo:w" not in wd.active_breaches()  # cumulative would stick


def test_breach_listeners_fire_on_every_occurrence():
    wd = Watchdog()
    reg = MetricsRegistry()
    h = reg.histogram("lat_ms")
    calls = []
    wd.add_breach_listener(lambda slo, ev, rising: calls.append(
        (slo, rising)))
    wd.watch_histogram_p99("w", h, ceiling_ms=10.0, windowed=True)
    wd.check_once()
    for sweep in range(3):
        for _ in range(30):
            h.observe(100.0)
        wd.check_once()
    assert [c for c in calls if c[0] == "slo:w"] == [
        ("slo:w", True), ("slo:w", False), ("slo:w", False)]
    wd.remove_breach_listener
    # the persistent breach dumped ONE rising-edge bundle, not three
    assert len(_dumps("slo:w")) == 1


# ============================================== scheduler brownout machine


def test_scheduler_brownout_throttle_pause_recover():
    wd = Watchdog()
    reg = MetricsRegistry()
    h = reg.histogram("lat_ms")
    cfg = CoresidentConfig(brownout_fraction=0.6, escalate_s=0.05,
                           recovery_s=0.05, poll_interval_s=0.01)
    sched = Scheduler(ledger=ResidencyLedger(limit_bytes=1 << 30),
                      config=cfg, watchdog=wd)
    try:
        wname = sched.guard_latency("m", h, slo_ms=100.0)
        assert wname == "coresident:m"
        # ceiling = 0.6 * SLO: throttling engages BEFORE the real SLO
        assert wd._hists[wname][1] == pytest.approx(60.0)
        wd.check_once()                          # arm the window
        for _ in range(30):
            h.observe(80.0)                      # > brownout, < SLO
        wd.check_once()
        assert sched.control.state == PauseControl.THROTTLE
        assert sched.stats()["throttles"] == 1
        time.sleep(0.06)                         # past escalate_s
        for _ in range(30):
            h.observe(80.0)
        wd.check_once()
        assert sched.control.state == PauseControl.PAUSE
        assert sched.stats()["pauses"] == 1
        time.sleep(0.06)                         # quiet past recovery_s
        sched._tick()
        assert sched.control.state == PauseControl.RUN
    finally:
        sched.close()
    assert wname not in wd._hists               # close unhooks the guard


def test_scheduler_negotiated_chunk_cap_shrinks_with_pressure():
    wd = Watchdog()
    reg = MetricsRegistry()
    h = reg.histogram("lat_ms")
    cfg = CoresidentConfig(chunk_cap=32, brownout_p99_ms=100.0)
    sched = Scheduler(ledger=ResidencyLedger(limit_bytes=1 << 30),
                      config=cfg, watchdog=wd)
    try:
        sched.guard_latency("m", h)
        assert sched.negotiate_chunk_cap() == 32     # no data: full cap
        for _ in range(100):
            h.observe(90.0)                          # ~90% of ceiling
        cap = sched.negotiate_chunk_cap()
        assert 1 <= cap <= 4                         # pow2, deep shrink
        assert (cap & (cap - 1)) == 0
    finally:
        sched.close()


def test_scheduler_refresh_infeasible_is_loud(tmp_path):
    X, y = _data(2, 400)
    lg = ResidencyLedger(limit_bytes=1 << 20)
    lg.lease("serve:m", lg.budget_bytes - 512, plane="serving")
    sched = Scheduler(ledger=lg, watchdog=Watchdog(),
                      workdir=str(tmp_path))
    try:
        with pytest.raises(CoresidencyInfeasible) as ei:
            sched.refresh("m", lgb.Dataset(X, label=y,
                                           free_raw_data=False),
                          PARAMS, 4)
        assert "serve:m" in str(ei.value)       # lease table in the verdict
    finally:
        sched.close()
    assert lg.leased_bytes(plane="train") == 0  # nothing leaked


def test_scheduler_refresh_trains_and_marks_fresh(tmp_path):
    X, y = _data(3, 1200)
    wd = Watchdog()
    sched = Scheduler(ledger=ResidencyLedger(limit_bytes=1 << 30),
                      watchdog=wd, workdir=str(tmp_path))
    try:
        wd.watch_freshness("m")
        booster, stats = sched.refresh(
            "m", lgb.Dataset(X, label=y, free_raw_data=False), PARAMS, 6)
        assert booster.current_iteration() == 6
        assert stats["pauses"] == 0
        age = wd.model_age_s("m")
        assert age is not None and age < 60.0
        # the training lease was released at completion
        assert sched.ledger.leased_bytes() == 0
    finally:
        sched.close()


def test_scheduler_refresh_rides_out_pause_byte_identical(tmp_path):
    X, y = _data(4, 1200)
    ref = lgb.train(dict(PARAMS),
                    lgb.Dataset(X, label=y, free_raw_data=False), 8,
                    verbose_eval=False)
    wd = Watchdog()
    cfg = CoresidentConfig(recovery_s=0.05, poll_interval_s=0.01,
                           max_pause_s=30.0, chunk_cap=1)
    sched = Scheduler(ledger=ResidencyLedger(limit_bytes=1 << 30),
                      config=cfg, watchdog=wd, workdir=str(tmp_path))
    fired = threading.Event()
    orig_consult = sched.control.consult

    def pausing_consult(i):
        if i >= 3 and not fired.is_set():
            fired.set()
            sched.control.request_pause()       # brownout strikes once
        return orig_consult(i)

    sched.control.consult = pausing_consult

    def unpause():
        fired.wait(timeout=30)
        time.sleep(0.05)
        sched.control.request_run()

    t = threading.Thread(target=unpause)
    t.start()
    try:
        booster, stats = sched.refresh(
            "m", lgb.Dataset(X, label=y, free_raw_data=False), PARAMS, 8)
    finally:
        t.join()
        sched.close()
    assert stats["pauses"] >= 1
    assert booster.model_to_string() == ref.model_to_string()


def test_paused_refresh_no_freshness_breach_storm():
    wd = Watchdog()
    wd.watch_freshness("fr", max_age_s=0.05)
    wd.mark_fresh("fr")
    time.sleep(0.08)                    # the refresh is paused: age grows
    ages = []
    for _ in range(4):
        wd.check_once()
        ages.append(global_registry.gauge(
            "model_age_seconds", labels={"model": "fr"}).value)
        time.sleep(0.02)
    # one rising-edge dump despite four breaching sweeps — no storm
    assert len(_dumps("freshness:fr")) == 1
    assert ages == sorted(ages)         # age is monotonic across the pause
    wd.mark_fresh("fr")                 # the resumed refresh completed
    wd.check_once()
    assert "freshness:fr" not in wd.active_breaches()
    assert global_registry.gauge(
        "model_age_seconds", labels={"model": "fr"}).value < ages[0]


# ==================================================== dual-plane device loss


@pytest.mark.chaos
def test_device_loss_replans_both_planes(tmp_path, monkeypatch):
    # the replan's apply_world mutates these OUTSIDE monkeypatch's
    # bookkeeping (delenv on an absent var records nothing) — pin them
    # so the shrunk world cannot leak into later tests
    for k in ("LGBM_TPU_NUM_SLICES", "LGBM_TPU_SLICE_DEVICES"):
        monkeypatch.delenv(k, raising=False)
        monkeypatch.setenv(k, "")
        monkeypatch.delenv(k)
    X, y = _data(5, 1500)
    deployed = lgb.train(dict(PARAMS),
                         lgb.Dataset(X, label=y, free_raw_data=False), 4,
                         verbose_eval=False)
    fleet = PodFleet(devices=2, max_batch_rows=128)
    fleet.add_model("live", deployed)
    fleet.warm()
    wd = Watchdog()
    cfg = CoresidentConfig(recovery_s=0.05, poll_interval_s=0.01,
                           chunk_cap=1, max_pause_s=30.0)
    sched = Scheduler(fleet=fleet, ledger=ResidencyLedger(
        limit_bytes=1 << 30), config=cfg, watchdog=wd,
        world={"num_slices": 2, "devices_per_slice": 1},
        workdir=str(tmp_path))
    result = {}

    def run_refresh():
        result["out"] = sched.refresh(
            "live", lgb.Dataset(X, label=y, free_raw_data=False),
            PARAMS, 20, init_model=deployed, swap=True)

    t = threading.Thread(target=run_refresh)
    t.start()
    try:
        # wait until training is demonstrably mid-flight
        deadline = time.monotonic() + 30
        while sched.control.consults < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert sched.control.consults >= 2
        fleet.kill_device(0)            # ONE coordinated replan:
        t.join(timeout=60)              # serving drains, training shrinks
        assert not t.is_alive()
        booster, stats = result["out"]
        assert booster.current_iteration() == 24
        # serving plane: survivor device serves the refreshed model
        assert fleet.live_devices() == [1]
        probe = X[:64]
        served = fleet.predict("live", probe, timeout=120)
        assert np.array_equal(served,
                              booster.predict(probe, raw_score=True))
        # training plane: the world shrank in the same replan
        assert sched.world == {"num_slices": 1, "devices_per_slice": 1}
        assert sched.stats()["device_losses"] == 1
        # the flight bundle names BOTH planes' outcomes
        bundles = _dumps("coresident:device_lost")
        assert len(bundles) == 1
        with open(os.path.join(global_flight.out_dir(), bundles[0])) as f:
            blob = f.read()
        assert "serving" in blob and "training" in blob
    finally:
        sched.close()
        fleet.close()


# =============================================== contention verdict + healthz


def test_contention_verdict_from_brownout_counters():
    from lightgbm_tpu.obs.diagnose import collect_signals, diagnose
    reg = MetricsRegistry()
    reg.counter("coresident_throttle_total").inc(3)
    reg.counter("coresident_pause_total").inc(1)
    reg.gauge("ledger_available_bytes").set(1000.0)
    prev = set_active_ledger(None)
    lg = ResidencyLedger(limit_bytes=1 << 20)
    lease = lg.lease("serve:m", 1000, plane="serving")
    set_active_ledger(lg)
    try:
        sig = collect_signals(registry=reg)
        assert sig["coresident_throttle_total"] == 3
        assert sig["coresident_pause_total"] == 1
        assert sig["ledger_lease_table"][0]["owner"] == "serve:m"
        verdicts = diagnose(sig)
        names = [v.name for v in verdicts]
        assert "contention" in names
        v = verdicts[names.index("contention")]
        assert v.evidence["coresident_throttle_total"] == 3
        assert v.evidence["ledger_lease_table"][0]["owner"] == "serve:m"
        assert 0.4 <= v.score <= 0.9
    finally:
        lg.release(lease)
        set_active_ledger(prev)


def test_no_contention_verdict_without_events():
    from lightgbm_tpu.obs.diagnose import collect_signals, diagnose
    sig = collect_signals(registry=MetricsRegistry())
    assert "contention" not in [v.name for v in diagnose(sig)]


@pytest.mark.obs
def test_healthz_degrades_on_active_breach():
    from lightgbm_tpu.obs.http import MetricsHTTPServer
    reg = MetricsRegistry()
    h = reg.histogram("lat_ms")
    srv = MetricsHTTPServer(registry=reg, port=0)
    try:
        port = srv.start()
        base = f"http://127.0.0.1:{port}"
        assert urllib.request.urlopen(
            f"{base}/healthz", timeout=5).read() == b"ok\n"
        h.observe(500.0)
        global_watchdog.watch_histogram_p99("hz_probe", h, ceiling_ms=1.0)
        global_watchdog.check_once()
        assert "slo:hz_probe" in global_watchdog.active_breaches()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/healthz", timeout=5)
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert body["status"] == "degraded"
        assert "slo:hz_probe" in body["breaches"]
        global_watchdog.unwatch_histogram("hz_probe")
        assert urllib.request.urlopen(
            f"{base}/healthz", timeout=5).read() == b"ok\n"
    finally:
        global_watchdog.unwatch_histogram("hz_probe")
        srv.stop()


# =========================================================== chaos delay


@pytest.mark.chaos
def test_device_delay_inflates_latency_without_failures():
    X, y = _data(6, 800)
    deployed = lgb.train(dict(PARAMS),
                         lgb.Dataset(X, label=y, free_raw_data=False), 4,
                         verbose_eval=False)
    chaos = ChaosRegistry([FaultSpec(site="device", kind="delay", at=i,
                                     arg=0.05) for i in range(2, 6)])
    fleet = PodFleet(devices=1, chaos=chaos, max_batch_rows=64)
    fleet.add_model("live", deployed)
    fleet.warm()
    try:
        lats = []
        for _ in range(8):
            t0 = time.perf_counter()
            fleet.predict("live", X[:16], timeout=60)
            lats.append(time.perf_counter() - t0)
        assert max(lats) >= 0.05            # the stall is visible...
        assert any("delay" in line for line in chaos.log)
    finally:
        fleet.close()                       # ...and nothing failed


# ====================================================== smoke tool (slow)


@pytest.mark.slow
def test_coresident_smoke_tool(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    from coresident_smoke import run_smoke
    summary = run_smoke(rows=2000, trees=6, refresh_trees=4, requests=60,
                        directory=str(tmp_path))
    assert not summary["failed"], json.dumps(summary["phase_ok"])
    assert all(summary["phase_ok"].values())
    co = summary["phases"]["coresidency"]
    assert not co["untyped_failures"]
    assert co["throttles"] > 0
    assert co["served_bit_equal_refreshed"]
