"""Distributed binning protocol (reference:
DatasetLoader::ConstructBinMappersFromTextData distributed branch,
src/io/dataset_loader.cpp:913-1000) driven over a simulated K-rank mesh
through the allgather injection seam (the LGBM_NetworkInitWithFunctions
analogue, c_api.h:1036)."""
import threading

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.dataset import Dataset
from lightgbm_tpu.parallel.dist_data import (construct_distributed,
                                             make_fake_allgather)

WORLD = 4


def _global_data(n=6000, f=7, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    X[:, 3] = np.where(rng.rand(n) < 0.6, 0.0, X[:, 3])   # sparse-ish col
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    return X, y


def _run_ranks(X, y, world=WORLD, params=None):
    """Each rank holds a contiguous row slice (the reference's data-parallel
    pre-partition); returns per-rank Datasets."""
    fn_for = make_fake_allgather(world)
    bounds = np.linspace(0, len(X), world + 1).astype(int)
    out = [None] * world
    errs = []

    def runner(r):
        try:
            lo, hi = bounds[r], bounds[r + 1]
            out[r] = construct_distributed(
                X[lo:hi], label=y[lo:hi], params=params or {},
                rank=r, world=world, allgather_bytes=fn_for(r))
        except Exception as e:       # pragma: no cover - surfaced below
            errs.append((r, e))

    threads = [threading.Thread(target=runner, args=(r,))
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errs, errs
    return out


def test_all_ranks_agree_on_mappers_and_layout():
    X, y = _global_data()
    parts = _run_ranks(X, y)
    ref = parts[0]
    for ds in parts[1:]:
        assert ds.used_features == ref.used_features
        assert ds.num_groups == ref.num_groups
        np.testing.assert_array_equal(ds.feat_group, ref.feat_group)
        np.testing.assert_array_equal(ds.feat_start, ref.feat_start)
        for ma, mb in zip(ds.bin_mappers, ref.bin_mappers):
            assert ma.num_bin == mb.num_bin
            np.testing.assert_array_equal(ma.bin_upper_bound,
                                          mb.bin_upper_bound)


def test_local_binned_matches_global_construct():
    """Concatenating the per-rank binned matrices must equal a
    single-process construct that sampled the same global rows."""
    X, y = _global_data()
    parts = _run_ranks(X, y)
    stacked = np.concatenate([ds.binned for ds in parts], axis=0)
    # single-process dataset with the full data and an exhaustive sample:
    # the distributed sample is also exhaustive (every rank samples all
    # local rows when sample_cnt >= n_local), so mappers coincide
    bulk = Dataset(X, label=y,
                   params={"bin_construct_sample_cnt": 10 ** 9}).construct()
    assert parts[0].used_features == bulk.used_features
    np.testing.assert_array_equal(stacked, bulk.binned)


def test_distributed_parts_train():
    """A rank's local Dataset trains through the normal engine."""
    X, y = _global_data()
    parts = _run_ranks(X, y, params={"min_data_in_leaf": 5})
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    parts[0], num_boost_round=3)
    assert bst.predict(X[:10]).shape == (10,)
