"""Pod-scale serving (fleet/topology.py + fleet/router.py): placement
election, replica bit-parity, hedged retries, brownout tiers, chaos
device loss with in-flight re-dispatch, and the availability SLO
(docs/SERVING.md multi-device section; docs/RESILIENCE.md failover
section).

All CPU-runnable under the tier-1 command.  Data is float32-precise so
the device backend's routing-exactness domain applies: every replica,
hedged, failed-over, and host-fallback response must be BIT-equal to
``Booster.predict(raw_score=True)``.
"""

import os
import sys
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.fleet import (DeviceSpec, Fleet, PodFleet, RouterConfig,
                                plan_devices, plan_topology)
from lightgbm_tpu.obs.metrics import MetricsRegistry
from lightgbm_tpu.obs.watchdog import SLOConfig, Watchdog, global_watchdog
from lightgbm_tpu.ops.planner import FleetModelShape, fleet_replica_bytes
from lightgbm_tpu.resilience.faults import ChaosRegistry
from lightgbm_tpu.serving import QueueFull
from lightgbm_tpu.serving.loadgen import fire_fleet_requests

pytestmark = pytest.mark.fleetscale

F = 10


@pytest.fixture
def flight_dir(tmp_path, monkeypatch):
    from lightgbm_tpu.obs.flight import global_flight
    monkeypatch.setattr(global_flight, "_out_dir", str(tmp_path))
    monkeypatch.setattr(global_flight, "dumps", 0)
    monkeypatch.setattr(global_flight, "enabled", True)
    return tmp_path


def _train(n=900, rounds=6, leaves=15, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, F).astype(np.float32).astype(np.float64)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    return lgb.train({"objective": "binary", "verbosity": -1,
                      "num_leaves": leaves},
                     lgb.Dataset(X, label=y), num_boost_round=rounds,
                     verbose_eval=False)


@pytest.fixture(scope="module")
def booster():
    return _train(seed=0)


def _pod(booster, devices=3, chaos=None, router=None, name="m",
         weight=2.0, deadline_class="interactive", **kw):
    pod = PodFleet(devices=devices, chaos=chaos,
                   router=router or RouterConfig(),
                   max_batch_rows=128, **kw)
    # generous deadlines: a first-compile stall on a loaded CI box must
    # not expire legitimate traffic (hedge/deadline mechanics get their
    # own pinned tests below)
    for cls in list(pod.deadline_classes):
        pod.deadline_classes[cls] = 60_000.0
    pod.add_model(name, booster, weight=weight,
                  deadline_class=deadline_class)
    return pod


def _f32_rows(rng, n):
    return rng.randn(n, F).astype(np.float32).astype(np.float64)


def _wait_for(cond, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# ------------------------------------------------------------ topology


def test_plan_devices_mesh_seam(monkeypatch):
    monkeypatch.delenv("LGBM_TPU_NUM_SLICES", raising=False)
    flat = plan_devices(4)
    assert [d.device_id for d in flat] == [0, 1, 2, 3]
    assert {d.slice_id for d in flat} == {0}
    monkeypatch.setenv("LGBM_TPU_NUM_SLICES", "2")
    hybrid = plan_devices(4)
    assert [d.slice_id for d in hybrid] == [0, 0, 1, 1]


def _shapes():
    return [
        FleetModelShape("hot", 100, 30, 31, F, buckets=(8, 64),
                        weight=8.0),
        FleetModelShape("warm", 100, 30, 31, F, buckets=(8, 64),
                        weight=2.0),
        FleetModelShape("cold", 100, 30, 31, F, buckets=(8, 64),
                        weight=1.0, age_s=300.0),
    ]


def test_plan_topology_replication_election():
    shapes = _shapes()
    fb, prog = fleet_replica_bytes(shapes[0], accel=False)
    one = fb + sum(prog.values())
    # each device fits ~1.5 replicas: the hot model must be replicated,
    # the cold tail partitioned one-per-device for capacity
    devs = [DeviceSpec(i, 0, int(one * 1.5 / 0.9)) for i in range(4)]
    tp = plan_topology(shapes, devs, accel=False)
    assert tp.feasible and tp.unplaced == ()
    assert len(tp.replicas["hot"]) > len(tp.replicas["cold"])
    assert all(len(ids) >= 1 for ids in tp.replicas.values())
    # every model's replica devices are distinct
    for ids in tp.replicas.values():
        assert len(set(ids)) == len(ids)
    # per-device residency plans cover exactly the placed replicas
    for d in tp.devices:
        placed = sorted(p.name for p in tp.placements
                        if p.device_id == d.device_id)
        assert sorted(m.name for m in
                      tp.device_plans[d.device_id].models) == placed
    # deterministic for identical inputs
    tp2 = plan_topology(_shapes(), devs, accel=False)
    assert tp2.replicas == tp.replicas
    import json
    json.dumps(tp.summary())        # JSON-able for journals


def test_plan_topology_ample_budget_replicates_everywhere():
    devs = [DeviceSpec(i, i // 2, 1 << 30) for i in range(4)]
    tp = plan_topology(_shapes(), devs, accel=False)
    assert all(len(ids) == 4 for ids in tp.replicas.values())


def test_plan_topology_unplaced_is_a_verdict_not_a_crash():
    devs = [DeviceSpec(0, 0, 1024)]     # fits nothing
    tp = plan_topology(_shapes(), devs, accel=False)
    assert not tp.feasible
    assert set(tp.unplaced) == {"hot", "warm", "cold"}


# ------------------------------------------------------- replica parity


def test_replica_bit_parity_and_pod_routing(booster, flight_dir):
    rng = np.random.RandomState(7)
    with _pod(booster, devices=3) as pod:
        pod.warm()
        assert len(pod.topology.replicas["m"]) == 3
        X = _f32_rows(rng, 40)
        expect = booster.predict(X, raw_score=True)
        # the routed path
        assert np.array_equal(pod.predict("m", X, timeout=60), expect)
        # every replica individually serves the same bits
        for r in list(pod._replicas["m"]):
            out = r.fleet.predict(r.inner_name, X, timeout=60)
            assert np.array_equal(out, expect)
        assert pod.availability("m") == 1.0


def test_pod_export_aot_per_device(booster, tmp_path, flight_dir):
    with _pod(booster, devices=2, aot_dir=str(tmp_path)) as pod:
        pod.warm()
        n = pod.export_aot()
        assert n > 0
        for did in pod.live_devices():
            sub = tmp_path / f"dev{did}"
            assert sub.is_dir() and any(sub.iterdir())


def test_pod_remove_model_drains_routing_table(booster, flight_dir):
    with _pod(booster, devices=2) as pod:
        rng = np.random.RandomState(3)
        X = _f32_rows(rng, 8)
        pod.predict("m", X, timeout=60)
        pod.remove_model("m")
        assert pod.models() == []
        from lightgbm_tpu.serving import ModelNotFound
        with pytest.raises(ModelNotFound):
            pod.predict("m", X, timeout=10)
        # the availability watch went with it
        assert "m" not in global_watchdog._avail


# ------------------------------------------------------------- hedging


def test_hedge_fires_only_after_hedge_deadline(booster, flight_dir):
    rng = np.random.RandomState(11)
    X = _f32_rows(rng, 8)
    expect = booster.predict(X, raw_score=True)
    # healthy pod: a fast request must NOT hedge even with hedging armed
    with _pod(booster, devices=2,
              router=RouterConfig(hedge_ms=2000.0)) as pod:
        pod.warm()
        assert np.array_equal(pod.predict("m", X, timeout=60), expect)
        assert pod.metrics.counter("fleet_hedges_total",
                                   labels={"model": "m"}).value == 0
    # wedged primary: the hedge fires at ~hedge_ms and the second
    # replica wins with identical bits
    chaos = ChaosRegistry("device.wedge@0:rank=0:sec=8")
    with _pod(booster, devices=2, chaos=chaos,
              router=RouterConfig(hedge_ms=150.0)) as pod:
        pod.warm()
        assert pod.topology.replicas["m"][0] == 0
        t0 = time.monotonic()
        out = pod.predict("m", X, timeout=30)
        lat_ms = (time.monotonic() - t0) * 1e3
        assert np.array_equal(out, expect)
        assert lat_ms >= 140.0, f"hedge fired early: {lat_ms:.1f} ms"
        assert pod.metrics.counter("fleet_hedges_total",
                                   labels={"model": "m"}).value == 1
        assert pod.metrics.counter("fleet_hedge_wins_total",
                                   labels={"model": "m"}).value == 1
        pod.close(drain=False, timeout=1.0)


# ------------------------------------------------------------ brownout


def test_brownout_tier_order(booster, flight_dir):
    rng = np.random.RandomState(5)
    X = _f32_rows(rng, 8)
    expect = booster.predict(X, raw_score=True)
    pod = PodFleet(devices=2, max_batch_rows=128)
    for cls in list(pod.deadline_classes):
        pod.deadline_classes[cls] = 60_000.0
    pod.add_model("m", booster, weight=1.0, deadline_class="batch",
                  brownout_precision="bf16", accuracy_budget=1.0)
    try:
        pod.warm()
        # tier 0: batch class serves normally, full precision
        assert np.array_equal(pod.predict("m", X, timeout=60), expect)
        # tier 1 (pinned pressure): batch class sheds TYPED
        pod._pressure = lambda name: 0.80
        with pytest.raises(QueueFull):
            pod.predict("m", X, timeout=10)
        assert pod.metrics.counter(
            "fleet_brownout_shed_total", labels={"model": "m"}).value == 1
        # tier 2: interactive-class traffic prefers the budgeted
        # lowprec twin (drift bounded by the declared accuracy budget)
        pod._pressure = lambda name: 0.88
        out = pod.predict("m", X, timeout=60,
                          request_class="interactive")
        assert np.max(np.abs(out - expect)) <= 1.0
        lp_requests = sum(
            r.fleet.metrics.counter("fleet_requests_total",
                                    labels={"model": "m!lp"}).value
            for r in pod._replicas["m"] if r.lowprec)
        assert lp_requests >= 1
        # tier 3: host-path fallback instead of cliff-edge QueueFull —
        # still bit-identical
        pod._pressure = lambda name: 0.97
        out3 = pod.predict("m", X, timeout=60,
                           request_class="interactive")
        assert np.array_equal(out3, expect)
        assert pod.metrics.counter(
            "fleet_host_fallback_total", labels={"model": "m"}).value >= 1
    finally:
        pod.close(drain=False, timeout=1.0)


# ------------------------------------------------------------ failover


def test_chaos_wedged_device_drains_with_inflight_redispatch(
        booster, flight_dir):
    rng = np.random.RandomState(13)
    X = _f32_rows(rng, 8)
    expect = booster.predict(X, raw_score=True)
    chaos = ChaosRegistry("device.wedge@0:rank=0:sec=6")
    router = RouterConfig(stale_beat_s=0.4, dead_strikes=2,
                          health_interval_s=0.1,
                          hedge_classes=())     # failover, not hedging
    with _pod(booster, devices=2, chaos=chaos, router=router) as pod:
        pod.warm()
        assert pod.topology.replicas["m"][0] == 0
        fut = pod.submit("m", X)        # lands on device 0, then wedges
        assert _wait_for(lambda: pod.metrics.counter(
            "fleet_devices_lost_total").value == 1, timeout=15.0), \
            "health sweep never declared the wedged device dead"
        # the stuck in-flight request was RE-DISPATCHED, not failed
        out = fut.result(timeout=15)
        assert np.array_equal(out, expect)
        assert pod.metrics.counter(
            "fleet_failover_redispatch_total",
            labels={"model": "m"}).value >= 1
        assert _wait_for(lambda: 0 not in pod.live_devices())
        # forensic bundle on failover (the drain thread writes it after
        # closing the dead device's servers — give it a moment)
        assert _wait_for(lambda: list(
            flight_dir.glob("flight_fleet_device_lost_*.json")))
        # new traffic keeps serving, bit-identical
        assert np.array_equal(pod.predict("m", X, timeout=30), expect)
        assert pod.availability("m") == 1.0
        pod.close(drain=False, timeout=1.0)


def test_chaos_vanished_device_is_a_replan_not_an_outage(
        booster, flight_dir):
    rng = np.random.RandomState(17)
    X = _f32_rows(rng, 16)
    expect = booster.predict(X, raw_score=True)
    chaos = ChaosRegistry()
    with _pod(booster, devices=3, chaos=chaos,
              router=RouterConfig(health_interval_s=0.1)) as pod:
        pod.warm()
        victim = pod.topology.replicas["m"][0]
        replans0 = pod.metrics.counter("fleet_replans_total").value
        chaos.down_device(victim, "vanish")
        # routing skips the vanished device immediately; health declares
        # it dead and the drain replans the topology over the survivors
        assert np.array_equal(pod.predict("m", X, timeout=30), expect)
        assert _wait_for(lambda: victim not in pod.live_devices())
        assert _wait_for(
            lambda: pod.topology is not None
            and victim not in pod.topology.replicas["m"]
            and len(pod.topology.replicas["m"]) >= 1)
        assert pod.metrics.counter(
            "fleet_replans_total").value > replans0
        assert pod.metrics.gauge("fleet_recovered_one_tick").value == 1
        assert np.array_equal(pod.predict("m", X, timeout=30), expect)
        pod.close(drain=False, timeout=1.0)


def test_fleet_remove_model_vs_replan_race(booster):
    """Bugfix audit: Fleet.remove_model drains under the replan lock, so
    hammering replan from threads while models churn never restores or
    drops arrays on a dying server."""
    fleet = Fleet(max_batch_rows=64)
    fleet.add_model("keep", booster)
    stop = threading.Event()
    errors = []

    def churn_replans():
        while not stop.is_set():
            try:
                fleet.replan()
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=churn_replans) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for i in range(8):
            fleet.add_model(f"m{i}", booster)
            fleet.remove_model(f"m{i}")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors
    rng = np.random.RandomState(1)
    X = _f32_rows(rng, 8)
    assert np.array_equal(fleet.predict("keep", X, timeout=60),
                          booster.predict(X, raw_score=True))
    fleet.close()


def test_pod_swap_model_flips_every_replica(booster, flight_dir):
    new = _train(seed=99, rounds=5)
    rng = np.random.RandomState(23)
    X = _f32_rows(rng, 12)
    pod = PodFleet(devices=2, max_batch_rows=128)
    for cls in list(pod.deadline_classes):
        pod.deadline_classes[cls] = 60_000.0
    pod.add_model("m", booster, weight=1.0,
                  brownout_precision="bf16", accuracy_budget=10.0)
    try:
        pod.warm()
        assert np.array_equal(pod.predict("m", X, timeout=60),
                              booster.predict(X, raw_score=True))
        pod.swap_model("m", new)
        expect = new.predict(X, raw_score=True)
        assert np.array_equal(pod.predict("m", X, timeout=60), expect)
        # every replica (and the host fallback model) flipped
        for r in list(pod._replicas["m"]):
            if not r.lowprec:
                out = r.fleet.predict(r.inner_name, X, timeout=60)
                assert np.array_equal(out, expect)
        assert np.array_equal(
            pod.entry("m").host_model.forest.predict_raw(X)[0], expect)
    finally:
        pod.close(drain=False, timeout=1.0)


# ------------------------------------------------ availability plumbing


def test_watchdog_availability_breach_and_rising_edge():
    dumps = []
    flight = SimpleNamespace(
        dump=lambda trigger, exc=None, extra=None: dumps.append(trigger))
    wd = Watchdog(config=SLOConfig(availability_floor=0.999),
                  registry=MetricsRegistry(), flight=flight)
    state = {"c": 10, "f": 0}
    wd.watch_availability("m0", lambda: (state["c"], state["f"]))
    assert wd.check_once() == []        # first sweep only banks state
    state.update(c=30)
    assert wd.check_once() == []        # clean window
    state.update(c=35, f=5)             # 5/10 failed this window
    breaches = wd.check_once()
    assert [b[0] for b in breaches] == ["availability:m0"]
    assert dumps == ["watchdog:availability:m0"]
    state.update(c=36, f=10)            # still breaching: no dump storm
    assert wd.check_once()
    assert len(dumps) == 1
    state.update(c=100, f=10)           # recovered: edge re-arms
    assert wd.check_once() == []
    state.update(c=101, f=20)
    assert wd.check_once()
    assert len(dumps) == 2
    wd.unwatch_availability("m0")
    assert wd.check_once() == []


def test_loadgen_availability_accounting():
    class StubFleet:
        def entry(self, name):
            return SimpleNamespace(
                model=SimpleNamespace(num_features=4, num_class=1))

        def predict(self, name, X, timeout=None):
            if name == "bad":
                raise RuntimeError("boom")
            return np.zeros(len(X))

    storm = fire_fleet_requests(StubFleet(), {"good": 1.0, "bad": 1.0},
                                60, 3, 5, timeout=5)
    o = storm["outcomes"]
    assert o["failed"] > 0 and o["completed"] > 0
    assert o["completed"] + o["shed"] + o["expired"] + o["failed"] \
        == storm["requests_planned"]
    assert storm["availability"] == pytest.approx(
        1.0 - o["failed"] / (o["completed"] + o["failed"]), abs=1e-6)
    assert storm["models"]["good"]["availability"] == 1.0
    assert storm["models"]["bad"]["availability"] == 0.0
    assert storm["models"]["bad"]["failed"] == o["failed"]
    assert not storm["errors"]          # failures are outcomes, not
    assert storm["failures"]            # dead threads


# -------------------------------------------------------------- stress


@pytest.mark.slow
def test_kill_under_load_stress(flight_dir):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    from fleet_smoke import run_failover_smoke
    summary = run_failover_smoke(devices=3, requests=900, threads=8)
    assert not summary["failed"], summary
    assert summary["availability"] >= 0.999
    assert summary["outcomes"]["failed"] == 0
    assert summary["recovered_within_one_tick"]
