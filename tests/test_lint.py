"""tpulint (tools/lint.py, docs/LINTING.md) — tier-1 enforcement.

The clean-tree test IS the enforcement point: every future PR runs the
whole static-analysis suite by default.  The fixture corpus
(tests/lint_fixtures/) proves each rule actually fires, line-exact, and
that pragmas/selectors/JSON output behave.
"""

import json
import os
import re
import subprocess
import sys

import pytest

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
RULES = {"env-flag-registry", "atomic-write", "traced-purity",
         "parity-hazard", "lock-discipline", "docs-sync"}

if REPO not in sys.path:
    sys.path.insert(0, REPO)


_CLI_CACHE = {}


def run_cli(*args):
    """One subprocess per distinct arg vector (the CLI is pure over an
    unchanged tree; several tests share the two canonical runs)."""
    if args in _CLI_CACHE:
        return _CLI_CACHE[args]
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"), *args],
        capture_output=True, text=True, cwd=REPO)
    verdict = None
    lines = r.stdout.strip().splitlines()
    if lines:
        try:
            verdict = json.loads(lines[-1])
        except ValueError:
            verdict = None
    _CLI_CACHE[args] = (r, verdict)
    return r, verdict


# ------------------------------------------------------------ the real tree

def test_repo_tree_is_clean():
    """THE gate: the shipped tree has zero violations and exits 0."""
    r, verdict = run_cli()
    assert r.returncode == 0, r.stdout + r.stderr
    assert verdict is not None and verdict["ok"] is True
    assert verdict["violations"] == 0
    assert set(verdict["rules"]) == RULES


# -------------------------------------------------------------- the corpus

def seeded_lines():
    """rule -> {(rel_path, line)} from the '# SEED <rule>' markers."""
    out = {}
    for dirpath, _dirs, files in os.walk(FIXTURES):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            rel = os.path.relpath(p, REPO)
            for i, line in enumerate(open(p), start=1):
                m = re.search(r"#\s*SEED\s+([a-z\-]+)", line)
                if m:
                    out.setdefault(m.group(1), set()).add((rel, i))
    return out


def test_fixture_corpus_every_rule_fires_line_exact():
    """Exit 1 on the corpus; every rule fires by name on EXACTLY the
    seeded (file, line) set — no misses, no false positives."""
    r, verdict = run_cli("tests/lint_fixtures")
    assert r.returncode == 1, r.stdout + r.stderr
    assert verdict["ok"] is False
    assert set(verdict["by_rule"]) == RULES

    reported = {}
    for line in r.stdout.splitlines():
        m = re.match(r"(\S+?):(\d+): \[([a-z\-]+)\]", line)
        if m:
            reported.setdefault(m.group(3), set()).add(
                (m.group(1), int(m.group(2))))
    seeds = seeded_lines()
    assert set(seeds) == RULES, "corpus must seed every rule"
    for rule in RULES:
        assert reported.get(rule) == seeds[rule], (
            f"{rule}: reported {sorted(reported.get(rule, ()))} != "
            f"seeded {sorted(seeds[rule])}")


def test_pragmas_silence_violations():
    """pragma_ok.py re-seeds env/write/traced violations behind line and
    file pragmas and must come back clean."""
    r, verdict = run_cli("tests/lint_fixtures/pragma_ok.py")
    assert r.returncode == 0, r.stdout + r.stderr
    assert verdict["violations"] == 0


def test_only_and_ignore_selectors():
    r, verdict = run_cli("tests/lint_fixtures", "--only", "atomic-write")
    assert r.returncode == 1
    assert set(verdict["by_rule"]) == {"atomic-write"}
    assert verdict["rules"] == ["atomic-write"]

    r2, verdict2 = run_cli("tests/lint_fixtures",
                           "--ignore", "atomic-write,traced-purity")
    assert r2.returncode == 1
    assert "atomic-write" not in verdict2["by_rule"]
    assert "traced-purity" not in verdict2["by_rule"]
    assert verdict2["by_rule"]  # others still fire


def test_unknown_rule_selector_exits_2():
    r, _ = run_cli("--only", "no-such-rule")
    assert r.returncode == 2
    assert "no-such-rule" in r.stderr


def test_missing_path_exits_2():
    """A typo'd path must NOT come back '0 files clean, exit 0'."""
    r, _ = run_cli("lightgbm_tpu/no_such_dir")
    assert r.returncode == 2
    assert "no_such_dir" in r.stderr
    r2, _ = run_cli("README.md")        # exists, but not lintable
    assert r2.returncode == 2


def test_unparseable_file_exits_2(tmp_path):
    """Null bytes / broken syntax are unusable input (exit 2 with a
    message), never a silent traceback or a fake 'violations' run."""
    bad = tmp_path / "bad.py"
    bad.write_bytes(b"x = 1\x00\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         str(bad)], capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 2, r.stdout + r.stderr
    assert "cannot load tree" in r.stderr


def test_traced_rule_covers_kwonly_and_posonly_params(tmp_path):
    """static_argnums maps over posonly+positional order; kw-only params
    are traced unless named in static_argnames."""
    fixture = tmp_path / "kern.py"
    fixture.write_text(
        "import jax\n"
        "import functools\n"
        "@functools.partial(jax.jit, static_argnums=(0,))\n"
        "def k(cfg, /, x, *, scale):\n"
        "    a = float(x)\n"          # traced -> flagged
        "    b = float(scale)\n"      # kw-only traced -> flagged
        "    if cfg:\n"               # static_argnums=(0,) -> cfg static
        "        a = a + 1\n"
        "    return a + b\n")
    from tools.lint import Project, SourceFile, run_lint, select_rules
    sf = SourceFile(str(fixture), "kern.py", fixture.read_text())
    vs = run_lint(Project([sf], root=REPO),
                  select_rules(only=["traced-purity"]))
    lines = sorted(v.line for v in vs)
    assert lines == [5, 6], [v.render() for v in vs]


def test_json_verdict_schema():
    """The last stdout line is machine-readable with the documented
    keys/types (the bench lint stage and CI parse this)."""
    for args in ((), ("tests/lint_fixtures",)):
        r, verdict = run_cli(*args)
        assert verdict is not None, r.stdout
        assert verdict["tool"] == "tpulint"
        assert isinstance(verdict["files"], int) and verdict["files"] > 0
        assert isinstance(verdict["rules"], list)
        assert isinstance(verdict["violations"], int)
        assert isinstance(verdict["by_rule"], dict)
        assert isinstance(verdict["ok"], bool)
        assert verdict["ok"] == (verdict["violations"] == 0)
        assert sum(verdict["by_rule"].values()) == verdict["violations"]


# ------------------------------------------------------- checker unit tests

def lint_paths(*paths, only=None):
    from tools.lint import load_project, run_lint, select_rules
    project = load_project(root=REPO, paths=list(paths))
    return run_lint(project, select_rules(only=only))


def test_lock_rule_negative_class_is_clean():
    """DisciplinedQueue (annotation + Condition alias + guarded-by-caller
    helper) must produce no lock-discipline findings."""
    vs = [v for v in lint_paths("tests/lint_fixtures/bad_locks.py",
                                only=["lock-discipline"])
          if "DisciplinedQueue" in v.message]
    assert vs == []


def test_traced_rule_static_and_partial_params_exempt():
    """static_argnames and functools.partial-bound params may drive
    Python branches; only genuinely traced params are flagged."""
    vs = lint_paths("tests/lint_fixtures/bad_traced.py",
                    only=["traced-purity"])
    assert not any(v.line > 30 for v in vs), \
        [v.render() for v in vs]  # build_partial/static_ok stay clean


def test_env_registry_is_complete_and_documented():
    """Programmatic twin of the clean-tree run: every registered flag
    carries a default+consumer+doc and its docfile mentions it."""
    from lightgbm_tpu.utils import envflags
    assert len(envflags.FLAGS) >= 38
    for flag in envflags.all_flags():
        assert flag.doc and flag.consumer and flag.docfile, flag.name
        doc = open(os.path.join(REPO, flag.docfile)).read()
        assert flag.name in doc, \
            f"{flag.name} missing from {flag.docfile}"
    # registry-backed accessor honors env + default
    assert envflags.get("BENCH_SMOKE_TREES") == "3"
    with pytest.raises(KeyError):
        envflags.get("LGBM_TPU_NOT_A_FLAG_EVER")


def test_bench_lint_stage_shape():
    """The bench 'lint' stage journals a clean verdict and raises (->
    never journaled) on a dirty tree: the Python API the stage uses
    agrees with the two cached CLI runs."""
    from tools.lint import load_project, run_lint
    project = load_project(root=REPO)
    violations = run_lint(project)
    assert violations == []
    # dirty-tree path: the corpus is dirty through the same API the
    # stage calls (CLI agreement already asserted above)
    _r, verdict = run_cli("tests/lint_fixtures")
    assert verdict["violations"] > 0


def test_gen_parameters_doc_shim_unchanged():
    """The standalone entrypoint still honors --check (exit 0, current)
    after the fold-in; the docs-sync rule shares its implementation."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "gen_parameters_doc.py"), "--check"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr + r.stdout
    from tools.lint import params_doc
    code, messages = params_doc.check()
    assert code == 0 and any("current" in m for m in messages)
