"""Test environment: force a virtual 8-device CPU mesh before JAX inits.

Mirrors SURVEY.md section 4's prescription: multi-host-simulated collective
tests with one process and 8 XLA CPU devices.  CPU is forced even when the
session has a real TPU attached so tests are deterministic and parallel-safe;
bench.py is the TPU entry point.

This image injects a TPU PJRT plugin into every interpreter via
sitecustomize, and JAX initializes every *registered* plugin on first
backend access — even under ``JAX_PLATFORMS=cpu`` — which blocks on the
TPU tunnel.  The plugin only registers a backend *factory*, so it can be
de-registered in-process any time before the first backend access; that is
what ``force_cpu_inprocess`` does (plus the host-device-count flag and the
persistent XLA compilation cache so repeated runs skip recompiles).
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the always-on flight recorder (obs/flight.py) dumps forensic bundles
# on quarantine/chaos triggers many tests exercise on purpose; keep the
# bundles out of the repo checkout (tests that assert on them point the
# recorder at their own tmp_path)
if "LIGHTGBM_TPU_FLIGHT_DIR" not in os.environ:
    os.environ["LIGHTGBM_TPU_FLIGHT_DIR"] = tempfile.mkdtemp(
        prefix="lgbt-flight-test-")

from lightgbm_tpu.utils.platform import force_cpu_inprocess  # noqa: E402

force_cpu_inprocess(8)
