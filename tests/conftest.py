"""Test environment: force a virtual 8-device CPU mesh before JAX imports.

Mirrors SURVEY.md section 4's prescription: multi-host-simulated collective
tests with one process and 8 XLA CPU devices.  CPU is forced even when the
session has a real TPU attached so tests are deterministic and parallel-safe;
bench.py is the TPU entry point.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
