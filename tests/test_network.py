"""Machine-list parsing / rank-resolution / init_network edge cases
(reference: Linkers::Linkers, linkers_socket.cpp:23-76).

Satellite of the resilience PR: a mistyped machine_list_file or a
non-positive listen_time_out must fail LOUDLY at init, not export
garbage into JAX_COORDINATION_SERVICE_TIMEOUT_SECS or silently train
single-machine.
"""
import socket

import pytest

from lightgbm_tpu.parallel.network import (init_network, parse_machine_list,
                                           resolve_rank)


def test_parse_machines_string_and_default_port():
    ml = parse_machine_list(machines="10.0.0.1:123,10.0.0.2,10.0.0.3:9")
    assert ml == [("10.0.0.1", 123), ("10.0.0.2", 12400), ("10.0.0.3", 9)]


def test_parse_machines_newline_separated():
    ml = parse_machine_list(machines="a:1\nb:2\n")
    assert ml == [("a", 1), ("b", 2)]


def test_parse_machine_list_file(tmp_path):
    f = tmp_path / "mlist.txt"
    f.write_text("hostA:5000\n\nhostB:5001\n")
    assert parse_machine_list(machine_list_file=str(f)) == \
        [("hostA", 5000), ("hostB", 5001)]


def test_parse_missing_machine_list_file_raises(tmp_path):
    with pytest.raises(ValueError, match="does not exist"):
        parse_machine_list(machine_list_file=str(tmp_path / "nope.txt"))


def test_parse_bad_port_raises():
    with pytest.raises(ValueError, match="not an integer"):
        parse_machine_list(machines="hostA:http")


def test_parse_empty_host_raises():
    with pytest.raises(ValueError, match="no host"):
        parse_machine_list(machines=":123")


def test_resolve_rank_by_position():
    me = socket.gethostname()
    ml = [("other-host-zzz", 1), (me, 2), ("another-host-yyy", 3)]
    assert resolve_rank(ml) == 1


def test_resolve_rank_duplicate_hosts_port_disambiguates():
    """Multi-process-per-host: the same hostname appears twice and
    local_listen_port picks the right slot."""
    me = "localhost"
    ml = [(me, 5000), (me, 5001), (me, 5002)]
    assert resolve_rank(ml, local_listen_port=5001) == 1
    assert resolve_rank(ml, local_listen_port=5002) == 2
    # unknown port: first local match wins (reference fallback)
    assert resolve_rank(ml, local_listen_port=9999) == 0
    assert resolve_rank(ml) == 0


def test_resolve_rank_no_match_raises():
    with pytest.raises(ValueError, match="matches this host"):
        resolve_rank([("host-that-is-not-us-1", 1),
                      ("host-that-is-not-us-2", 2)])


def test_init_network_truncates_list_to_num_machines():
    coord, n, rank = init_network(
        machines="localhost:12400,localhost:12401,ghost:12402",
        local_listen_port=12401, num_machines=2, dry_run=True)
    assert (coord, n, rank) == ("localhost:12400", 2, 1)


def test_init_network_num_machines_exceeding_list_raises():
    with pytest.raises(ValueError, match="machine list has"):
        init_network(machines="localhost:12400", num_machines=3,
                     dry_run=True)


def test_init_network_missing_file_raises(tmp_path):
    with pytest.raises(ValueError, match="does not exist"):
        init_network(machine_list_file=str(tmp_path / "missing.txt"),
                     dry_run=True)


@pytest.mark.parametrize("bad", [0, -1, -120])
def test_init_network_rejects_nonpositive_timeout(bad):
    with pytest.raises(ValueError, match="listen_time_out"):
        init_network(machines="localhost:12400,localhost:12401",
                     listen_time_out=bad, dry_run=True)


def test_init_network_no_list_single_machine_is_noop():
    assert init_network(dry_run=True) is None
