"""Out-of-core streaming data plane (lightgbm_tpu/data/).

The hard contracts:

- streamed training == resident training BYTE-identical (model text)
  for quantized payloads, and bit-identical in pinned block order for
  f32 (the resident comparator pins the rounds grower — the streamed
  grower mirrors it op for op);
- the two-level budget planner (ops/planner.plan_stream) elects
  streaming exactly when residency blows either the device or the host
  budget, and sizes blocks to fit both;
- the spill store is checksummed: corruption raises loudly, never
  wrong trees; writes are atomic; spill-mode loads keep host RSS
  O(chunk);
- push_rows validates overlap/gaps instead of silently overwriting;
- checkpoints resume mid-stream bit-identically, across modes.
"""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.data.blockstore import (BlockStore, BlockStoreCorruptError)
from lightgbm_tpu.data.stream import BlockPump, host_rss_bytes
from lightgbm_tpu.dataset import Dataset
from lightgbm_tpu.ops.planner import (plan_stream, predict_host_peak_bytes)

RNG = np.random.RandomState(7)
N, F = 1200, 10
X = RNG.randn(N, F)
Y_BIN = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + 0.2 * RNG.randn(N) > 0).astype(float)
XV = RNG.randn(400, F)
YV_BIN = (XV[:, 0] + 0.5 * XV[:, 1] * XV[:, 2]
          + 0.2 * RNG.randn(400) > 0).astype(float)

BASE = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
        "verbosity": -1, "tpu_tree_growth": "rounds"}

PARITY_CASES = {
    "f32": {},
    "quant": {"use_quantized_grad": True},
    "quant_renew": {"use_quantized_grad": True,
                    "quant_train_renew_leaf": True},
    "bagging": {"bagging_fraction": 0.7, "bagging_freq": 2,
                "bagging_seed": 11},
    "goss": {"boosting": "goss", "learning_rate": 0.2},
    "l1_renew": {"objective": "regression_l1"},
    "multiclass": {"objective": "multiclass", "num_class": 3,
                   "num_leaves": 7},
}


def _stream_env(monkeypatch, block_rows=256):
    monkeypatch.setenv("LGBM_TPU_STREAM", "1")
    monkeypatch.setenv("LGBM_TPU_STREAM_BLOCK_ROWS", str(block_rows))


def _train(params, y=Y_BIN, rounds=12, x=None):
    ds = lgb.Dataset(X if x is None else x, label=y, free_raw_data=False)
    b = lgb.Booster(params=dict(BASE, **params), train_set=ds)
    for _ in range(rounds):
        b.update()
    return b


# ---------------------------------------------------------------- parity

@pytest.mark.parametrize("case", sorted(PARITY_CASES))
def test_streamed_equals_resident(case, monkeypatch):
    params = PARITY_CASES[case]
    y = Y_BIN
    if case == "multiclass":
        y = np.digitize(X[:, 0] + X[:, 1], [-0.5, 0.5]).astype(float)
    monkeypatch.setenv("LGBM_TPU_STREAM", "0")
    resident = _train(params, y).model_to_string()
    _stream_env(monkeypatch)
    b = _train(params, y)
    assert b.boosting._stream is not None, "stream election did not engage"
    assert b.model_to_string() == resident, \
        f"{case}: streamed != resident model text"


def test_streamed_block_size_invariance(monkeypatch):
    """Quantized folds are associative: ANY block partition gives the
    byte-identical model (f32 pins ONE block order; quant pins none)."""
    params = {"use_quantized_grad": True}
    _stream_env(monkeypatch, block_rows=256)
    m256 = _train(params).model_to_string()
    _stream_env(monkeypatch, block_rows=500)
    m500 = _train(params).model_to_string()
    assert m256 == m500


def test_streamed_engine_train_with_valid(monkeypatch):
    """Full engine path: eval history, valid scores, metric_freq — the
    streamed booster must reproduce the resident run exactly."""
    def run():
        ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
        vs = lgb.Dataset(XV, label=YV_BIN, reference=ds,
                         free_raw_data=False)
        evals = {}
        bst = lgb.train(dict(BASE, metric="binary_logloss"), ds,
                        num_boost_round=10, valid_sets=[vs],
                        evals_result=evals, verbose_eval=False)
        return bst.model_to_string(), evals

    monkeypatch.setenv("LGBM_TPU_STREAM", "0")
    m_r, ev_r = run()
    _stream_env(monkeypatch)
    m_s, ev_s = run()
    assert m_s == m_r
    assert ev_s == ev_r


def test_resume_mid_stream(tmp_path, monkeypatch):
    """A checkpoint written mid-stream resumes to the byte-identical
    final model — within streamed mode AND restored into a resident
    run (streamed == resident is bit-invariant, so bundles cross)."""
    snap = str(tmp_path / "m.txt")
    params = dict(BASE, bagging_fraction=0.7, bagging_freq=1)

    def run(stream, resume=None):
        if stream:
            _stream_env(monkeypatch)
        else:
            monkeypatch.setenv("LGBM_TPU_STREAM", "0")
        ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
        return lgb.train(params, ds, num_boost_round=14,
                         verbose_eval=False, snapshot_freq=5,
                         snapshot_out=snap,
                         resume_from=resume).model_to_string()

    full = run(True)
    assert run(True, resume=snap + ".ckpt") == full
    assert run(False, resume=snap + ".ckpt") == full


def test_checkpoint_records_stream_provenance(tmp_path, monkeypatch):
    import glob
    import zipfile
    _stream_env(monkeypatch)
    snap = str(tmp_path / "m.txt")
    ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
    lgb.train(BASE, ds, num_boost_round=4, verbose_eval=False,
              snapshot_freq=2, snapshot_out=snap)
    bundle = sorted(glob.glob(snap + ".ckpt/*.lgbckpt"))[-1]
    with zipfile.ZipFile(bundle) as zf:
        man = json.loads(zf.read("manifest.json"))
    sp = man["stream_plan"]
    assert sp is not None and sp["stream"]
    assert sp["store_num_blocks"] >= 2
    assert sp["store_block_rows"] == 256


def test_stream_unsupported_config_falls_back_resident(monkeypatch):
    """A forced stream election with a config the streamed executor does
    not cover warns and trains resident instead of failing."""
    _stream_env(monkeypatch)
    b = _train({"objective": "regression",
                "monotone_constraints": [1] + [0] * (F - 1)}, y=X[:, 0])
    assert b.boosting._stream is None
    assert b.num_trees() == 12


def test_chunk_scheduler_declines_streamed(monkeypatch):
    _stream_env(monkeypatch)
    ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
    b = lgb.Booster(params=dict(BASE), train_set=ds)
    assert b.boosting._stream is not None
    assert not b.boosting.chunk_supported()
    with pytest.raises(RuntimeError, match="per-iteration"):
        b.update_chunk(4)


# ------------------------------------------------- spill-mode construction

def test_from_sample_spill_trains_and_matches(monkeypatch, tmp_path):
    n, f = 4000, 6
    rng = np.random.RandomState(3)
    Xs = rng.rand(n, f)
    ys = (Xs[:, 0] + Xs[:, 1] > 1.0).astype(np.float32)
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "tpu_tree_growth": "rounds"}

    ds = Dataset.from_sample(Xs[:1000], n, spill=str(tmp_path / "st"),
                             spill_block_rows=512)
    for lo in range(0, n, 700):        # ragged final chunk (5*700 + 500)
        ds.push_rows(Xs[lo:lo + 700])
    assert ds.constructed and ds.binned is None
    assert ds._block_store.num_blocks == 8
    ds.set_label(ys)
    b = lgb.Booster(params=p, train_set=ds)
    assert b.boosting._stream is not None
    for _ in range(5):
        b.update()
    spilled = b.model_to_string()

    monkeypatch.setenv("LGBM_TPU_STREAM", "0")
    ds2 = Dataset.from_sample(Xs[:1000], n)
    for lo in range(0, n, 700):
        ds2.push_rows(Xs[lo:lo + 700])
    ds2.set_label(ys)
    b2 = lgb.Booster(params=p, train_set=ds2)
    for _ in range(5):
        b2.update()
    assert spilled == b2.model_to_string()


def test_push_rows_overlap_raises():
    ds = Dataset.from_sample(X[:300], N)
    ds.push_rows(X[:400])
    with pytest.raises(ValueError, match="overlap"):
        ds.push_rows(X[300:600], start_row=300)
    # disjoint explicit ranges still fine (out-of-order fill)
    ds.push_rows(X[800:], start_row=800)
    ds.push_rows(X[400:800], start_row=400)
    assert ds.constructed


def test_push_rows_spill_gap_raises(tmp_path):
    ds = Dataset.from_sample(X[:300], N, spill=str(tmp_path / "st"),
                             spill_block_rows=256)
    ds.push_rows(X[:400])
    with pytest.raises(ValueError, match="append in order"):
        ds.push_rows(X[600:], start_row=600)


def test_incomplete_stream_construct_names_gap():
    ds = Dataset.from_sample(X[:300], N)
    ds.push_rows(X[:400])
    with pytest.raises(RuntimeError, match="first unpushed row: 400"):
        ds.construct()


def test_binned_metadata_accessors(monkeypatch):
    # released matrix: shape/dtype stay valid, data access raises
    monkeypatch.setenv("LGBM_TPU_FREE_BINNED", "1")
    monkeypatch.setenv("LGBM_TPU_STREAM", "0")
    ds = lgb.Dataset(X, label=Y_BIN)
    lgb.Booster(params=dict(BASE), train_set=ds)
    assert ds.binned is None
    assert ds.binned_shape() == (N, ds.num_groups)
    assert ds.binned_dtype() == np.uint8
    with pytest.raises(RuntimeError, match="released"):
        ds.host_binned()
    # block-backed matrix (free_raw_data=True releases the host copy
    # after the spill): same metadata, block-store-specific error
    monkeypatch.delenv("LGBM_TPU_FREE_BINNED")
    _stream_env(monkeypatch)
    ds2 = lgb.Dataset(X, label=Y_BIN)
    lgb.Booster(params=dict(BASE), train_set=ds2)
    assert ds2.binned is None and ds2._block_store is not None
    assert ds2.binned_shape() == (N, ds2.num_groups)
    with pytest.raises(RuntimeError, match="block store"):
        ds2.host_binned()


def test_spill_keeps_host_matrix_when_raw_kept(monkeypatch):
    """free_raw_data=False keeps the host matrix next to the spill store
    (the user asked for reuse); free_raw_data=True releases it."""
    _stream_env(monkeypatch)
    ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
    lgb.Booster(params=dict(BASE), train_set=ds)
    assert ds.binned is not None
    ds2 = lgb.Dataset(X, label=Y_BIN)
    lgb.Booster(params=dict(BASE), train_set=ds2)
    assert ds2.binned is None


# ----------------------------------------------------------- block store

def test_blockstore_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    arr = rng.randint(0, 200, (1000, 7), dtype=np.uint8)
    st = BlockStore.from_array(str(tmp_path / "st"), arr, 256)
    assert st.num_blocks == 4                      # 256*3 + 232 ragged
    st2 = BlockStore.open(str(tmp_path / "st"))
    got = np.concatenate([np.asarray(st2.read_block(i)).T
                          for i in range(st2.num_blocks)])
    np.testing.assert_array_equal(got, arr)
    # readinto path returns the same bytes
    buf = np.empty((7, st2.block_rows), np.uint8)
    view = st2.read_block(0, out=buf, verify=True)
    np.testing.assert_array_equal(view, np.asarray(st2.read_block(0)))


def test_blockstore_ragged_chunk_composition(tmp_path):
    rng = np.random.RandomState(1)
    arr = rng.randint(0, 255, (900, 4), dtype=np.uint8)
    st = BlockStore.create(str(tmp_path / "st"), 900, 4, np.uint8, 128)
    for lo, hi in ((0, 50), (50, 500), (500, 900)):   # uneven appends
        st.append_rows(arr[lo:hi])
    st.finalize()
    st2 = BlockStore.open(str(tmp_path / "st"))
    got = np.concatenate([np.asarray(st2.read_block(i)).T
                          for i in range(st2.num_blocks)])
    np.testing.assert_array_equal(got, arr)


def test_blockstore_corruption_raises(tmp_path):
    rng = np.random.RandomState(2)
    arr = rng.randint(0, 255, (600, 5), dtype=np.uint8)
    path = str(tmp_path / "st")
    BlockStore.from_array(path, arr, 256)
    victim = os.path.join(path, "block_00001.bin")
    raw = bytearray(open(victim, "rb").read())
    raw[17] ^= 0xFF
    with open(victim, "wb") as fh:
        fh.write(raw)
    st = BlockStore.open(path)
    st.read_block(0)                               # intact block fine
    with pytest.raises(BlockStoreCorruptError, match="checksum"):
        st.read_block(1)
    buf = np.empty((5, st.block_rows), np.uint8)
    with pytest.raises(BlockStoreCorruptError, match="checksum"):
        st.read_block(1, out=buf, verify=True)


def test_blockstore_corrupt_training_fails_loudly(tmp_path, monkeypatch):
    """End to end: a corrupted spill block must ABORT streamed training,
    not produce silently wrong trees."""
    _stream_env(monkeypatch)
    ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
    b = lgb.Booster(params=dict(BASE), train_set=ds)
    b.update()
    store = ds._block_store
    victim = os.path.join(store.path, "block_00002.bin")
    raw = bytearray(open(victim, "rb").read())
    raw[3] ^= 0x40
    with open(victim, "wb") as fh:
        fh.write(raw)
    store._verified.discard(2)                     # fresh-process read
    with pytest.raises(BlockStoreCorruptError, match="checksum"):
        b.update()


def test_blockstore_unfinalized_refused(tmp_path):
    st = BlockStore.create(str(tmp_path / "st"), 100, 3, np.uint8, 64)
    st.append_rows(np.zeros((100, 3), np.uint8))
    with pytest.raises(BlockStoreCorruptError, match="manifest"):
        BlockStore.open(str(tmp_path / "st"))
    with pytest.raises(RuntimeError, match="not finalized"):
        st.read_block(0)
    st.finalize()
    assert BlockStore.open(str(tmp_path / "st")).num_blocks == 2


def test_block_pump_prefetch_matches_serial(tmp_path):
    rng = np.random.RandomState(4)
    arr = rng.randint(0, 255, (1000, 6), dtype=np.uint8)
    st = BlockStore.from_array(str(tmp_path / "st"), arr, 128)
    a = [(i, s, r, np.asarray(blk))
         for (i, s, r, blk) in BlockPump(st, prefetch=True)]
    b = [(i, s, r, np.asarray(blk))
         for (i, s, r, blk) in BlockPump(st, prefetch=False)]
    assert [x[:3] for x in a] == [x[:3] for x in b]
    for (_, _, _, xa), (_, _, _, xb) in zip(a, b):
        np.testing.assert_array_equal(xa, xb)


# ----------------------------------------------------------- planner

def test_plan_stream_resident_when_both_fit():
    p = plan_stream(rows=10_000, features=8, num_bins=64,
                    device_budget_bytes=1 << 33, host_budget_bytes=1 << 33)
    assert not p.stream and p.feasible
    assert p.resident_device_ok and p.resident_host_ok
    assert p.reason == "resident fits both budgets"


def test_plan_stream_elects_on_device_budget():
    p = plan_stream(rows=50_000_000, features=28, num_bins=64,
                    device_budget_bytes=3 << 30,
                    host_budget_bytes=1 << 40)
    assert p.stream and not p.resident_device_ok and p.resident_host_ok
    assert "device" in p.reason
    assert p.block_rows > 0 and p.num_blocks >= 2
    assert p.predicted_device_peak_bytes <= p.device_budget_bytes


def test_plan_stream_elects_on_host_budget():
    p = plan_stream(rows=50_000_000, features=28, num_bins=64,
                    device_budget_bytes=1 << 40,
                    host_budget_bytes=2 << 30)
    assert p.stream and p.resident_device_ok and not p.resident_host_ok
    assert "host" in p.reason
    assert p.predicted_host_peak_bytes <= p.host_budget_bytes


def test_plan_stream_infeasible_verdict():
    p = plan_stream(rows=1_000_000_000, features=28, num_bins=64,
                    device_budget_bytes=1 << 26, host_budget_bytes=1 << 26)
    assert p.stream and not p.feasible


def test_plan_stream_env_overrides(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_STREAM", "0")
    p = plan_stream(rows=50_000_000, features=28, num_bins=64,
                    device_budget_bytes=1 << 28, host_budget_bytes=1 << 28)
    assert not p.stream and "disabled" in p.reason
    monkeypatch.setenv("LGBM_TPU_STREAM", "1")
    monkeypatch.setenv("LGBM_TPU_STREAM_BLOCK_ROWS", "4096")
    p = plan_stream(rows=100_000, features=8, num_bins=64,
                    device_budget_bytes=1 << 33, host_budget_bytes=1 << 33)
    assert p.stream and p.block_rows == 4096 and p.num_blocks == 25


def test_predict_host_peak_streaming_beats_resident():
    res = predict_host_peak_bytes(100_000_000, 28, 1)[0]
    stream = predict_host_peak_bytes(100_000_000, 28, 1, 1 << 20)[0]
    # the O(n) per-row metadata (labels/weights) stays in both modes;
    # the matrix term itself drops to O(block)
    assert stream < res / 4
    # and scales with the block, not the rows
    small = predict_host_peak_bytes(100_000_000, 28, 1, 1 << 16)[0]
    assert small < stream


def test_stream_plan_in_manifest_summary_roundtrips():
    p = plan_stream(rows=1_000_000, features=8, num_bins=64,
                    device_budget_bytes=1 << 24, host_budget_bytes=1 << 40)
    s = p.summary()
    assert json.loads(json.dumps(s)) == s


# ----------------------------------------------------------- tooling

@pytest.mark.perf
def test_stream_probe_json():
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from stream_probe import run_probe
    out = run_probe(rows=60_000, features=6, block_rows=8192, passes=1)
    assert out["spill"]["rows_per_sec"] > 0
    assert out["pump"]["blocks_per_sec"] > 0
    assert out["pump"]["overlap_efficiency"] > 0
    assert out["host_rss"]["predicted_stream_peak_bytes"] > 0
    assert host_rss_bytes() > 0
    json.dumps(out)
