"""Pluggable file IO + two-round streamed text loading.

reference: VirtualFileReader/Writer + USE_HDFS backend (src/io/file_io.cpp)
and the two_round big-file loader (config.h:570, dataset_loader.cpp:775).
"""
import io
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.dataset import Dataset
from lightgbm_tpu.utils.file_io import (open_file, register_file_system,
                                        unregister_file_system)


def _csv(tmp_path, n=20000, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] + X[:, 1] > 1).astype(np.float32)
    path = os.path.join(str(tmp_path), "d.csv")
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.8g")
    return path, X, y


def test_two_round_matches_one_shot(tmp_path):
    path, X, y = _csv(tmp_path)
    d1 = Dataset(path).construct()
    d2 = Dataset(path, params={"two_round": True}).construct()
    np.testing.assert_array_equal(d1.binned, d2.binned)
    np.testing.assert_array_equal(d1.metadata.label, d2.metadata.label)
    assert d1.used_features == d2.used_features


def test_two_round_trains_via_engine(tmp_path):
    path, X, y = _csv(tmp_path)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "two_round": True},
                    lgb.Dataset(path), num_boost_round=3)
    assert bst.predict(X[:10]).shape == (10,)


def test_two_round_sidecar_query(tmp_path):
    """.query sidecar loads in the streamed path too (metadata.cpp
    LoadQueryBoundaries analogue)."""
    path, X, y = _csv(tmp_path, n=3000)
    group = np.full(100, 30, np.int64)
    np.savetxt(path + ".query", group, fmt="%d")
    d = Dataset(path, params={"two_round": True}).construct()
    assert d.metadata.num_queries() == 100


def test_registered_scheme_round_trip(tmp_path):
    store = {}

    class _W(io.StringIO):
        def __init__(self, key):
            super().__init__()
            self.key = key

        def close(self):
            store[self.key] = self.getvalue()
            super().close()

    def opener(path, mode="r"):
        if "w" in mode:
            return _W(path)
        return io.StringIO(store[path])

    register_file_system("mem", opener)
    try:
        rng = np.random.RandomState(0)
        X = rng.rand(500, 4)
        y = (X[:, 0] > 0.5).astype(np.float32)
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=2)
        bst.save_model("mem://model")
        again = lgb.Booster(model_file="mem://model")
        np.testing.assert_allclose(bst.predict(X[:5]), again.predict(X[:5]),
                                   rtol=1e-12)
    finally:
        unregister_file_system("mem")


def test_unregistered_scheme_errors():
    with pytest.raises(OSError):
        open_file("nosuchscheme12345://x", "r")
