"""C-API-shaped seam (reference: tests/c_api_test/test_.py drives
lib_lightgbm.so with raw ctypes — same flow here through capi.py)."""
import numpy as np

from lightgbm_tpu import capi


def _data(n=2000, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] + X[:, 1] > 1).astype(np.float32)
    return X, y


def test_full_train_predict_flow(tmp_path):
    """Mirrors the reference c_api_test: dataset -> booster -> 20 iters ->
    eval -> save/load -> prediction parity (tests/c_api_test/test_.py:12)."""
    X, y = _data()
    dh, vh, bh = [0], [0], [0]
    assert capi.LGBM_DatasetCreateFromMat(
        X, "max_bin=63 min_data_in_leaf=10", y, dh) == 0
    assert capi.LGBM_DatasetCreateFromMat(X, "max_bin=63", y, vh) == 0
    assert capi.LGBM_BoosterCreate(
        dh[0], "objective=binary num_leaves=15 verbosity=-1 metric=auc",
        bh) == 0
    assert capi.LGBM_BoosterAddValidData(bh[0], vh[0]) == 0
    fin = [0]
    for _ in range(20):
        assert capi.LGBM_BoosterUpdateOneIter(bh[0], fin) == 0
        if fin[0]:
            break
    out_n = [0]
    assert capi.LGBM_BoosterNumberOfTotalModel(bh[0], out_n) == 0
    assert out_n[0] > 0
    ev = []
    assert capi.LGBM_BoosterGetEval(bh[0], 1, ev) == 0
    assert len(ev) == 1 and ev[0] > 0.8          # valid AUC

    pred = [None]
    assert capi.LGBM_BoosterPredictForMat(bh[0], X[:100], 0, -1, pred) == 0
    path = str(tmp_path / "model.txt")
    assert capi.LGBM_BoosterSaveModel(bh[0], 0, -1, path) == 0
    nh, it = [0], [0]
    assert capi.LGBM_BoosterCreateFromModelfile(path, it, nh) == 0
    pred2 = [None]
    assert capi.LGBM_BoosterPredictForMat(nh[0], X[:100], 0, -1, pred2) == 0
    np.testing.assert_allclose(pred[0], pred2[0], rtol=1e-12)

    for h in (dh[0], vh[0]):
        assert capi.LGBM_DatasetFree(h) == 0
    for h in (bh[0], nh[0]):
        assert capi.LGBM_BoosterFree(h) == 0


def test_streaming_push_via_capi():
    """reference: c_api.h:98-144 streaming flow through the seam."""
    X, y = _data(n=3000)
    dh, bh = [0], [0]
    assert capi.LGBM_DatasetCreateFromSampledColumn(
        X[:1000], len(X), "max_bin=63", dh) == 0
    assert capi.LGBM_DatasetPushRows(dh[0], X[:1500], 0) == 0
    assert capi.LGBM_DatasetPushRows(dh[0], X[1500:], 1500) == 0
    assert capi.LGBM_DatasetSetField(dh[0], "label", y) == 0
    out = [0]
    assert capi.LGBM_DatasetGetNumData(dh[0], out) == 0
    assert out[0] == len(X)
    assert capi.LGBM_BoosterCreate(
        dh[0], "objective=binary num_leaves=7 verbosity=-1", bh) == 0
    fin = [0]
    assert capi.LGBM_BoosterUpdateOneIter(bh[0], fin) == 0


def test_error_protocol():
    """Failures return -1 and report through LGBM_GetLastError — never
    raise across the seam (reference ABI convention, c_api.h:58)."""
    out = [0]
    rc = capi.LGBM_DatasetGetNumData(999999, out)
    assert rc == -1
    assert "invalid handle" in capi.LGBM_GetLastError()
    rc = capi.LGBM_DatasetSetField(999999, "label", [1.0])
    assert rc == -1


def test_custom_objective_update():
    X, y = _data(n=1000)
    dh, bh = [0], [0]
    assert capi.LGBM_DatasetCreateFromMat(X, "", y, dh) == 0
    assert capi.LGBM_BoosterCreate(
        dh[0], "objective=regression num_leaves=7 verbosity=-1", bh) == 0
    # plain L2 gradients supplied externally
    from lightgbm_tpu import capi as c
    import lightgbm_tpu as lgb
    bst = c._get(bh[0])
    score = np.zeros(len(y), np.float32)
    fin = [0]
    for _ in range(3):
        grad = score - y
        hess = np.ones_like(grad)
        assert c.LGBM_BoosterUpdateOneIterCustom(bh[0], grad, hess, fin) == 0
        score = bst.predict(X, raw_score=True).astype(np.float32)
    mse = float(np.mean((score - y) ** 2))
    assert mse < float(np.mean((0 - y) ** 2))
