"""C-API-shaped seam (reference: tests/c_api_test/test_.py drives
lib_lightgbm.so with raw ctypes — same flow here through capi.py)."""
import numpy as np

from lightgbm_tpu import capi


def _data(n=2000, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] + X[:, 1] > 1).astype(np.float32)
    return X, y


def test_full_train_predict_flow(tmp_path):
    """Mirrors the reference c_api_test: dataset -> booster -> 20 iters ->
    eval -> save/load -> prediction parity (tests/c_api_test/test_.py:12)."""
    X, y = _data()
    dh, vh, bh = [0], [0], [0]
    assert capi.LGBM_DatasetCreateFromMat(
        X, "max_bin=63 min_data_in_leaf=10", y, dh) == 0
    assert capi.LGBM_DatasetCreateFromMat(X, "max_bin=63", y, vh) == 0
    assert capi.LGBM_BoosterCreate(
        dh[0], "objective=binary num_leaves=15 verbosity=-1 metric=auc",
        bh) == 0
    assert capi.LGBM_BoosterAddValidData(bh[0], vh[0]) == 0
    fin = [0]
    for _ in range(20):
        assert capi.LGBM_BoosterUpdateOneIter(bh[0], fin) == 0
        if fin[0]:
            break
    out_n = [0]
    assert capi.LGBM_BoosterNumberOfTotalModel(bh[0], out_n) == 0
    assert out_n[0] > 0
    ev = []
    assert capi.LGBM_BoosterGetEval(bh[0], 1, ev) == 0
    assert len(ev) == 1 and ev[0] > 0.8          # valid AUC

    pred = [None]
    assert capi.LGBM_BoosterPredictForMat(bh[0], X[:100], 0, -1, pred) == 0
    path = str(tmp_path / "model.txt")
    assert capi.LGBM_BoosterSaveModel(bh[0], 0, -1, path) == 0
    nh, it = [0], [0]
    assert capi.LGBM_BoosterCreateFromModelfile(path, it, nh) == 0
    pred2 = [None]
    assert capi.LGBM_BoosterPredictForMat(nh[0], X[:100], 0, -1, pred2) == 0
    np.testing.assert_allclose(pred[0], pred2[0], rtol=1e-12)

    for h in (dh[0], vh[0]):
        assert capi.LGBM_DatasetFree(h) == 0
    for h in (bh[0], nh[0]):
        assert capi.LGBM_BoosterFree(h) == 0


def test_streaming_push_via_capi():
    """reference: c_api.h:98-144 streaming flow through the seam."""
    X, y = _data(n=3000)
    dh, bh = [0], [0]
    assert capi.LGBM_DatasetCreateFromSampledColumn(
        X[:1000], len(X), "max_bin=63", dh) == 0
    assert capi.LGBM_DatasetPushRows(dh[0], X[:1500], 0) == 0
    assert capi.LGBM_DatasetPushRows(dh[0], X[1500:], 1500) == 0
    assert capi.LGBM_DatasetSetField(dh[0], "label", y) == 0
    out = [0]
    assert capi.LGBM_DatasetGetNumData(dh[0], out) == 0
    assert out[0] == len(X)
    assert capi.LGBM_BoosterCreate(
        dh[0], "objective=binary num_leaves=7 verbosity=-1", bh) == 0
    fin = [0]
    assert capi.LGBM_BoosterUpdateOneIter(bh[0], fin) == 0


def test_error_protocol():
    """Failures return -1 and report through LGBM_GetLastError — never
    raise across the seam (reference ABI convention, c_api.h:58)."""
    out = [0]
    rc = capi.LGBM_DatasetGetNumData(999999, out)
    assert rc == -1
    assert "invalid handle" in capi.LGBM_GetLastError()
    rc = capi.LGBM_DatasetSetField(999999, "label", [1.0])
    assert rc == -1


def test_custom_objective_update():
    X, y = _data(n=1000)
    dh, bh = [0], [0]
    assert capi.LGBM_DatasetCreateFromMat(X, "", y, dh) == 0
    assert capi.LGBM_BoosterCreate(
        dh[0], "objective=regression num_leaves=7 verbosity=-1", bh) == 0
    # plain L2 gradients supplied externally
    from lightgbm_tpu import capi as c
    import lightgbm_tpu as lgb
    bst = c._get(bh[0])
    score = np.zeros(len(y), np.float32)
    fin = [0]
    for _ in range(3):
        grad = score - y
        hess = np.ones_like(grad)
        assert c.LGBM_BoosterUpdateOneIterCustom(bh[0], grad, hess, fin) == 0
        score = bst.predict(X, raw_score=True).astype(np.float32)
    mse = float(np.mean((score - y) ** 2))
    assert mse < float(np.mean((0 - y) ** 2))


def test_round4_capi_surface(tmp_path):
    """The remaining c_api.h surface: CSR create, subset, by-reference
    streaming, predict variants, dump/importance/bounds/leaf access,
    merge/shuffle, param checking, network shims."""
    from scipy import sparse

    X, y = _data(1200, 6)
    sp = sparse.csr_matrix(X)
    dh, bh = [0], [0]
    assert capi.LGBM_DatasetCreateFromCSR(
        sp.indptr, sp.indices, sp.data, X.shape[0], X.shape[1],
        "max_bin=31 min_data_in_leaf=5", y, 0, dh) == 0
    nd = [0]
    assert capi.LGBM_DatasetGetNumData(dh[0], nd) == 0 and nd[0] == 1200
    assert capi.LGBM_BoosterCreate(
        dh[0], "objective=binary num_leaves=7 verbosity=-1 metric=auc",
        bh) == 0
    fin = [0]
    for _ in range(5):
        assert capi.LGBM_BoosterUpdateOneIter(bh[0], fin) == 0

    # eval names/counts
    names, cnt = [], [0]
    assert capi.LGBM_BoosterGetEvalCounts(bh[0], cnt) == 0 and cnt[0] == 1
    assert capi.LGBM_BoosterGetEvalNames(bh[0], names) == 0
    assert names == ["auc"]

    # feature names / num feature
    fnames, nf = [], [0]
    assert capi.LGBM_BoosterGetNumFeature(bh[0], nf) == 0 and nf[0] == 6
    assert capi.LGBM_BoosterGetFeatureNames(bh[0], fnames) == 0
    assert len(fnames) == 6

    # predict variants agree
    p_mat, p_csr, p_row, p_mats = [None], [None], [None], [None]
    assert capi.LGBM_BoosterPredictForMat(bh[0], X[:8], 0, -1, p_mat) == 0
    s8 = sparse.csr_matrix(X[:8])
    assert capi.LGBM_BoosterPredictForCSR(
        bh[0], s8.indptr, s8.indices, s8.data, 8, 6, 0, -1, p_csr) == 0
    np.testing.assert_allclose(p_csr[0], p_mat[0], rtol=1e-6)
    assert capi.LGBM_BoosterPredictForMatSingleRow(
        bh[0], X[0], 0, -1, p_row) == 0
    np.testing.assert_allclose(p_row[0][0], p_mat[0][0], rtol=1e-6)
    assert capi.LGBM_BoosterPredictForMats(
        bh[0], [X[0], X[1]], 0, -1, p_mats) == 0
    np.testing.assert_allclose(p_mats[0], p_mat[0][:2], rtol=1e-6)

    # calc num predict: leaf and contrib sizes
    out = [0]
    assert capi.LGBM_BoosterCalcNumPredict(bh[0], 10, 2, -1, out) == 0
    assert out[0] == 10 * 5
    assert capi.LGBM_BoosterCalcNumPredict(bh[0], 10, 3, -1, out) == 0
    assert out[0] == 10 * 7

    # dump / importance / bounds / leaf values
    js = [None]
    assert capi.LGBM_BoosterDumpModel(bh[0], 0, -1, js) == 0
    import json
    assert len(json.loads(js[0])["tree_info"]) == 5
    imp = [None]
    assert capi.LGBM_BoosterFeatureImportance(bh[0], -1, 0, imp) == 0
    assert imp[0].sum() > 0
    lo, hi = [0.0], [0.0]
    assert capi.LGBM_BoosterGetLowerBoundValue(bh[0], lo) == 0
    assert capi.LGBM_BoosterGetUpperBoundValue(bh[0], hi) == 0
    assert lo[0] <= hi[0]
    lv = [0.0]
    assert capi.LGBM_BoosterGetLeafValue(bh[0], 0, 0, lv) == 0
    assert capi.LGBM_BoosterSetLeafValue(bh[0], 0, 0, lv[0] + 1.0) == 0
    lv2 = [0.0]
    assert capi.LGBM_BoosterGetLeafValue(bh[0], 0, 0, lv2) == 0
    assert abs(lv2[0] - lv[0] - 1.0) < 1e-9

    # inner predict scores
    npred, scores = [0], [None]
    assert capi.LGBM_BoosterGetNumPredict(bh[0], 0, npred) == 0
    assert capi.LGBM_BoosterGetPredict(bh[0], 0, scores) == 0
    assert scores[0].shape[0] == 1200

    # merge + shuffle
    bh2 = [0]
    assert capi.LGBM_BoosterCreate(
        dh[0], "objective=binary num_leaves=7 verbosity=-1", bh2) == 0
    assert capi.LGBM_BoosterUpdateOneIter(bh2[0], fin) == 0
    total = [0]
    assert capi.LGBM_BoosterMerge(bh[0], bh2[0]) == 0
    assert capi.LGBM_BoosterNumberOfTotalModel(bh[0], total) == 0
    assert total[0] == 6
    assert capi.LGBM_BoosterShuffleModels(bh[0], 0, -1) == 0

    # subset
    sub = [0]
    assert capi.LGBM_DatasetGetSubset(
        dh[0], np.arange(100, 300), "", sub) == 0
    assert capi.LGBM_DatasetGetNumData(sub[0], nd) == 0 and nd[0] == 200

    # by-reference streaming push
    ref_stream = [0]
    assert capi.LGBM_DatasetCreateByReference(dh[0], 100, ref_stream) == 0
    assert capi.LGBM_DatasetPushRows(ref_stream[0], X[:60], None) == 0
    s2 = sparse.csr_matrix(X[60:100])
    assert capi.LGBM_DatasetPushRowsByCSR(
        ref_stream[0], s2.indptr, s2.indices, s2.data, 40, None) == 0
    assert capi.LGBM_DatasetGetNumData(ref_stream[0], nd) == 0

    # param checking
    assert capi.LGBM_DatasetUpdateParamChecking(
        "max_bin=31", "max_bin=31 learning_rate=0.2") == 0
    assert capi.LGBM_DatasetUpdateParamChecking(
        "max_bin=31", "max_bin=63") == -1
    assert "max_bin" in capi.LGBM_GetLastError()

    # predict-for-file round trip
    data_f = tmp_path / "pred_in.csv"
    np.savetxt(data_f, X[:10], delimiter=",", fmt="%.6f")
    out_f = tmp_path / "pred_out.txt"
    assert capi.LGBM_BoosterPredictForFile(
        bh[0], str(data_f), 0, 0, -1, str(out_f)) == 0
    got = np.loadtxt(out_f)
    assert got.shape[0] == 10

    # dataset field get + feature names + dump text
    field = [None]
    assert capi.LGBM_DatasetGetField(dh[0], "label", field) == 0
    assert field[0].shape[0] == 1200
    assert capi.LGBM_DatasetSetFeatureNames(
        dh[0], [f"f{i}" for i in range(6)]) == 0
    got_names = []
    assert capi.LGBM_DatasetGetFeatureNames(dh[0], got_names) == 0
    assert got_names == [f"f{i}" for i in range(6)]
    assert capi.LGBM_DatasetDumpText(dh[0], str(tmp_path / "dump.txt")) == 0

    # network entry points: single-machine init is a clean no-op; a list
    # without this host reports the error through LGBM_GetLastError
    import socket
    assert capi.LGBM_NetworkInit(
        f"{socket.gethostname()}:12400", 12400, 120, 1) == 0
    assert capi.LGBM_NetworkInit("10.255.1.1:1,10.255.1.2:2", 12400, 120,
                                 2) == -1
    assert "matches this host" in capi.LGBM_GetLastError()
    assert capi.LGBM_NetworkFree() == 0
    # external collective injection is unsupported: must FAIL FAST (a
    # caller believing distributed aggregation is wired would otherwise
    # train divergent partition-local models)
    assert capi.LGBM_NetworkInitWithFunctions(2, 0, None, None) != 0
    assert "NetworkInitWithFunctions" in capi.LGBM_GetLastError()


def test_reset_training_data_replays_scores():
    """LGBM_BoosterResetTrainingData must keep the existing trees' score
    contributions (GBDT::ResetTrainingData replays AddScore)."""
    X, y = _data(1500, 4, seed=3)
    dh, bh = [0], [0]
    assert capi.LGBM_DatasetCreateFromMat(
        X, "max_bin=31 free_raw_data=false", y, dh) == 0
    assert capi.LGBM_BoosterCreate(
        dh[0], "objective=binary num_leaves=7 verbosity=-1 metric=binary_logloss",
        bh) == 0
    fin = [0]
    for _ in range(5):
        assert capi.LGBM_BoosterUpdateOneIter(bh[0], fin) == 0
    ev0 = []
    assert capi.LGBM_BoosterGetEval(bh[0], 0, ev0) == 0

    X2, y2 = _data(1500, 4, seed=4)
    dh2 = [0]
    assert capi.LGBM_DatasetCreateFromMat(
        X2, "max_bin=31 free_raw_data=false", y2, dh2) == 0
    assert capi.LGBM_BoosterResetTrainingData(bh[0], dh2[0]) == 0
    # training continues from the existing model: its first eval on the
    # new data must be much better than an untrained model's (replayed
    # scores), and further iterations must improve it
    ev1 = []
    assert capi.LGBM_BoosterGetEval(bh[0], 0, ev1) == 0
    assert ev1[0] < 0.6                     # logloss with replayed model
    for _ in range(3):
        assert capi.LGBM_BoosterUpdateOneIter(bh[0], fin) == 0
    ev2 = []
    assert capi.LGBM_BoosterGetEval(bh[0], 0, ev2) == 0
    assert ev2[0] < ev1[0]
    total = [0]
    assert capi.LGBM_BoosterNumberOfTotalModel(bh[0], total) == 0
    assert total[0] == 8


def test_eval_names_follow_parameter_and_data_resets():
    """GetEvalNames must track metric-list changes from ResetParameter
    (reference ResetConfig re-creates metrics) and survive a training-data
    swap; booster attributes survive ResetTrainingData."""
    X, y = _data(1000, 4, seed=5)
    dh, bh = [0], [0]
    assert capi.LGBM_DatasetCreateFromMat(
        X, "max_bin=31 free_raw_data=false", y, dh) == 0
    assert capi.LGBM_BoosterCreate(
        dh[0], "objective=binary num_leaves=7 verbosity=-1 "
        "metric=binary_logloss", bh) == 0
    names, cnt = [], [0]
    assert capi.LGBM_BoosterGetEvalNames(bh[0], names) == 0
    assert names == ["binary_logloss"]
    assert capi.LGBM_BoosterResetParameter(bh[0], "metric=auc,binary_error") == 0
    assert capi.LGBM_BoosterGetEvalNames(bh[0], names) == 0
    assert names == ["auc", "binary_error"]
    assert capi.LGBM_BoosterGetEvalCounts(bh[0], cnt) == 0
    assert cnt[0] == 2

    # Python-side booster attributes (attrs are a basic.py concern in the
    # reference too) survive a training-data swap; eval names keep working
    bst = capi._get(bh[0])
    bst.set_attr(note="kept")
    bst.set_train_data_name("mytrain")
    fin = [0]
    assert capi.LGBM_BoosterUpdateOneIter(bh[0], fin) == 0
    X2, y2 = _data(1000, 4, seed=6)
    dh2 = [0]
    assert capi.LGBM_DatasetCreateFromMat(
        X2, "max_bin=31 free_raw_data=false", y2, dh2) == 0
    assert capi.LGBM_BoosterResetTrainingData(bh[0], dh2[0]) == 0
    bst = capi._get(bh[0])
    assert bst.attr("note") == "kept"
    assert bst._train_data_name == "mytrain"
    assert capi.LGBM_BoosterGetEvalNames(bh[0], names) == 0
    assert names == ["auc", "binary_error"]
