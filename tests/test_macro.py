"""Fused multi-iteration macro-steps (lightgbm_tpu/boosting/macro.py).

The hard contract: chunked training composes the SAME iter_body in one
runtime-trip-count loop program, so ``update_chunk(c)`` must produce
models BYTE-IDENTICAL to per-iteration ``update()`` for every supported
mode and every chunk decomposition — serial and sharded, eager and
deferred-host, through checkpoints and early stopping.
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

RNG = np.random.RandomState(7)
N, F = 1200, 10
X = RNG.randn(N, F)
Y_BIN = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.2 * RNG.randn(N) > 0).astype(float)
Y_REG = (X[:, 0] - X[:, 1] + 0.1 * RNG.randn(N))
Y_MC = np.digitize(X[:, 0] + X[:, 1], [-0.5, 0.5]).astype(float)

XV = RNG.randn(400, F)
YV_BIN = (XV[:, 0] + 0.5 * XV[:, 1] * XV[:, 2] + 0.2 * RNG.randn(400) > 0).astype(float)

PARITY_CASES = {
    "gbdt": ({"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.1}, Y_BIN),
    "bagging": ({"objective": "binary", "num_leaves": 15,
                 "learning_rate": 0.1, "bagging_fraction": 0.7,
                 "bagging_freq": 2, "bagging_seed": 11}, Y_BIN),
    "goss": ({"objective": "binary", "boosting": "goss", "num_leaves": 15,
              "learning_rate": 0.2}, Y_BIN),
    "rf": ({"objective": "binary", "boosting": "rf", "num_leaves": 15,
            "bagging_fraction": 0.6, "bagging_freq": 1}, Y_BIN),
    "monotone": ({"objective": "regression", "num_leaves": 15,
                  "learning_rate": 0.1,
                  "monotone_constraints": [1, -1] + [0] * (F - 2)}, Y_REG),
    "multiclass": ({"objective": "multiclass", "num_class": 3,
                    "num_leaves": 7, "learning_rate": 0.1}, Y_MC),
    # quantized-gradient mode: the in-loop discretization (stochastic
    # rounding keys ride the stacked per-round key stream) must keep
    # chunked == per-iteration byte-identical WITHIN the mode
    "quant": ({"objective": "binary", "num_leaves": 15,
               "learning_rate": 0.1, "use_quantized_grad": True}, Y_BIN),
    "quant_renew": ({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.1, "use_quantized_grad": True,
                     "quant_train_renew_leaf": True,
                     "bagging_fraction": 0.7, "bagging_freq": 2,
                     "bagging_seed": 11}, Y_BIN),
    # fused Pallas histogram→split megakernel arm (ops/fused.py, CPU
    # interpret mode): the in-kernel scan + VMEM arena must keep chunked
    # == per-iteration byte-identical, f32 and quantized
    "fused": ({"objective": "binary", "num_leaves": 15,
               "learning_rate": 0.1, "tpu_hist_method": "fused"}, Y_BIN),
    "fused_quant": ({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.1, "tpu_hist_method": "fused",
                     "use_quantized_grad": True,
                     "bagging_fraction": 0.7, "bagging_freq": 2,
                     "bagging_seed": 11}, Y_BIN),
}


def _booster(params, y, **ds_kw):
    params = dict(params, verbosity=-1)
    ds = lgb.Dataset(X, label=y, free_raw_data=False, **ds_kw)
    return lgb.Booster(params=params, train_set=ds)


def _train(params, y, chunks):
    b = _booster(params, y)
    for c in chunks:
        if c > 1:
            b.update_chunk(c)
        else:
            b.update()
    return b.model_to_string()


@pytest.mark.parametrize("case", sorted(PARITY_CASES))
def test_chunked_equals_per_iteration(case):
    params, y = PARITY_CASES[case]
    per_iter = _train(params, y, [1] * 12)
    chunked = _train(params, y, [8, 4])
    mixed = _train(params, y, [2, 1, 4, 2, 2, 1])
    assert chunked == per_iter, f"{case}: chunk(8,4) != per-iteration"
    assert mixed == per_iter, f"{case}: mixed chunks != per-iteration"


@pytest.mark.parametrize("case", ["gbdt", "quant", "fused_quant"])
def test_chunked_equals_per_iteration_tiled(case, monkeypatch):
    """Planner row tiling active (LGBM_TPU_TILE_ROWS forces tiles far
    smaller than n): chunked == per-iteration must hold unchanged, and
    the tiled models must equal the untiled ones byte-for-byte (the
    kernels' pinned tile-major accumulation order)."""
    params, y = PARITY_CASES[case]
    untiled = _train(params, y, [1] * 12)
    monkeypatch.setenv("LGBM_TPU_TILE_ROWS", "256")
    per_iter = _train(params, y, [1] * 12)
    chunked = _train(params, y, [8, 4])
    assert chunked == per_iter, f"{case}: tiled chunk(8,4) != per-iter"
    assert per_iter == untiled, f"{case}: tiled != untiled"


@pytest.mark.parametrize("case", ["gbdt", "quant"])
def test_chunked_equals_per_iteration_hierarchical(case, monkeypatch):
    """Hybrid ("dcn","ici") mesh with hierarchical tiered reduction
    (pod-scale plane, parallel/collectives.py): chunked == per-iteration
    must hold unchanged, and the hierarchical models must equal the
    flat-schedule ones byte-for-byte — integer payloads are associative;
    the f32 row rides the pinned tier-ordered reduction."""
    params, y = PARITY_CASES[case]
    params = dict(params, tree_learner="data")
    monkeypatch.setenv("LGBM_TPU_NUM_SLICES", "2")
    if case == "gbdt":
        monkeypatch.setenv("LGBM_TPU_PINNED_REDUCE", "1")
    monkeypatch.setenv("LGBM_TPU_HIER_REDUCE", "0")
    flat = _train(params, y, [1] * 12)
    monkeypatch.setenv("LGBM_TPU_HIER_REDUCE", "1")
    per_iter = _train(params, y, [1] * 12)
    chunked = _train(params, y, [8, 4])
    assert chunked == per_iter, f"{case}: hierarchical chunk != per-iter"
    assert per_iter == flat, f"{case}: hierarchical != flat schedule"


def _strip_hist_method_lines(text):
    return "\n".join(ln for ln in text.splitlines()
                     if not ln.startswith("[tpu_hist_method"))


@pytest.mark.parametrize("mesh", ["flat8", "2x4", "4x2"])
def test_sharded_fused_quant_byte_parity(mesh, monkeypatch):
    """The collective seam (grower_rounds.py sharded fused arm):
    data-parallel quantized fused == staged BYTE-identical model text
    across the flat 8-device mesh and both hybrid ("dcn","ici") tier
    shapes — the seam psums the same integer smaller-child arena through
    the same psum_quant_hist routing and the scan body is shared, so
    equality is exact, not approximate."""
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs the virtual 8-device mesh")
    if mesh != "flat8":
        monkeypatch.setenv("LGBM_TPU_NUM_SLICES", mesh.split("x")[0])
        monkeypatch.setenv("LGBM_TPU_HIER_REDUCE", "1")
    params = dict(PARITY_CASES["quant"][0], tree_learner="data",
                  tpu_tree_growth="rounds")
    staged = _strip_hist_method_lines(_train(params, Y_BIN, [1] * 8))
    fused = _strip_hist_method_lines(
        _train(dict(params, tpu_hist_method="fused"), Y_BIN, [1] * 8))
    assert fused == staged, f"{mesh}: sharded fused != staged"


def test_fused_categorical_tree_parity():
    """The lifted categorical gate: per-category stats are the same
    segment reduction, so the fused arm's cat merge (pick_fused_best)
    must reproduce the staged categorical split search — quantized mode,
    byte-identical model text."""
    rng = np.random.RandomState(21)
    Xc = np.column_stack([rng.randint(0, 8, N).astype(float), X[:, 1:]])
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.1, "use_quantized_grad": True,
              "tpu_tree_growth": "rounds", "verbosity": -1}

    def run(method):
        ds = lgb.Dataset(Xc, label=Y_BIN, free_raw_data=False,
                         categorical_feature=[0])
        b = lgb.Booster(params=dict(params, tpu_hist_method=method),
                        train_set=ds)
        if method == "fused":
            assert b.boosting.grower_cfg.hist_method == "fused"
        for _ in range(8):
            b.update()
        return _strip_hist_method_lines(b.model_to_string())

    staged = run("auto")
    fused = run("fused")
    assert fused == staged
    # the categorical feature must actually split somewhere, or the
    # parity above proved nothing about the cat merge
    assert "cat_threshold" in fused or "split_feature=0" in fused


@pytest.mark.parametrize("case", ["gbdt", "quant"])
def test_streamed_equals_resident_chunk_matrix(case, monkeypatch):
    """Out-of-core streamed training (lightgbm_tpu/data/) joins the
    chunked==per-iteration matrix: the streamed executor must reproduce
    the resident models byte-for-byte — quant by integer associativity,
    f32 by the pinned-block-order carry fold — under BOTH chunk-gate
    settings (streamed training is per-iteration by construction, so
    the scheduler's c=1 fallback must change nothing)."""
    params, y = PARITY_CASES[case]
    params = dict(params, tpu_tree_growth="rounds")  # the streamed
    # grower mirrors the rounds grower; pin the resident comparator
    monkeypatch.setenv("LGBM_TPU_STREAM", "0")
    resident = _train(params, y, [1] * 12)
    resident_chunked = _train(params, y, [8, 4])
    monkeypatch.setenv("LGBM_TPU_STREAM", "1")
    monkeypatch.setenv("LGBM_TPU_STREAM_BLOCK_ROWS", "256")
    streamed = _train(params, y, [1] * 12)
    assert resident_chunked == resident
    assert streamed == resident, f"{case}: streamed != resident"

    # engine runs (the chunk SCHEDULER in play): engine-streamed must
    # equal engine-resident under both gate settings
    def run_engine(stream, chunk):
        monkeypatch.setenv("LGBM_TPU_STREAM", "1" if stream else "0")
        monkeypatch.setenv("LGBM_TPU_CHUNK", chunk)
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        return lgb.train(dict(params, verbosity=-1), ds,
                         num_boost_round=12,
                         verbose_eval=False).model_to_string()

    engine_resident = run_engine(False, "32")
    for env in ("0", "32"):
        assert run_engine(True, env) == engine_resident, \
            f"{case}: streamed engine run (chunk={env}) != resident"


def test_chunked_equals_per_iteration_deferred_host(monkeypatch):
    """The deferred-host banking path (accelerator default) slices the
    chunk bundle into per-iteration pending entries; the drain must see
    exactly what per-iteration training banks."""
    monkeypatch.setenv("LGBT_DEFER_HOST_TREES", "1")
    params, y = PARITY_CASES["gbdt"]
    assert _train(params, y, [8, 4]) == _train(params, y, [1] * 12)


def test_chunked_equals_per_iteration_sharded():
    """Data-parallel over the virtual 8-device CPU mesh: the chunk scan
    wraps the shard_map'd iter_body; stacked row inputs keep the row
    sharding (parallel/learners.py put_stacked_rows)."""
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.1, "tree_learner": "data"}
    assert _train(params, Y_BIN, [8, 4]) == _train(params, Y_BIN, [1] * 12)


def test_lr_schedule_parity_via_engine():
    """reset_parameter learning-rate schedules ride into the chunk as a
    [c] array; engine-chunked training must equal per-iteration."""
    sched = [0.1 * (0.97 ** i) for i in range(16)]

    def run(env):
        os.environ["LGBM_TPU_CHUNK"] = env
        try:
            ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
            return lgb.train(
                {"objective": "binary", "num_leaves": 15, "verbosity": -1},
                ds, num_boost_round=16, learning_rates=sched,
                verbose_eval=False).model_to_string()
        finally:
            os.environ.pop("LGBM_TPU_CHUNK", None)

    assert run("32") == run("0")


def test_early_stopping_parity_via_engine():
    def run(env):
        os.environ["LGBM_TPU_CHUNK"] = env
        try:
            ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
            vs = lgb.Dataset(XV, label=YV_BIN, reference=ds,
                             free_raw_data=False)
            evals = {}
            bst = lgb.train(
                {"objective": "binary", "num_leaves": 31, "verbosity": -1,
                 "metric": "binary_logloss", "metric_freq": 2},
                ds, num_boost_round=60, valid_sets=[vs],
                early_stopping_rounds=4, evals_result=evals,
                verbose_eval=False)
            return bst.best_iteration, bst.model_to_string(), evals
        finally:
            os.environ.pop("LGBM_TPU_CHUNK", None)

    it_on, model_on, ev_on = run("32")
    it_off, model_off, ev_off = run("0")
    assert it_on == it_off
    assert model_on == model_off
    assert ev_on == ev_off


def test_rf_valid_scores_parity_via_engine():
    """RF's running-mean valid-score renormalization rides the fused
    valid updater (macro.build_chunk_valid rf mode); eval history and
    model must match per-iteration training."""
    def run(env):
        os.environ["LGBM_TPU_CHUNK"] = env
        try:
            ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
            vs = lgb.Dataset(XV, label=YV_BIN, reference=ds,
                             free_raw_data=False)
            evals = {}
            bst = lgb.train(
                {"objective": "binary", "boosting": "rf", "num_leaves": 15,
                 "bagging_fraction": 0.6, "bagging_freq": 1,
                 "verbosity": -1, "metric": "binary_logloss",
                 "metric_freq": 4},
                ds, num_boost_round=8, valid_sets=[vs],
                evals_result=evals, verbose_eval=False)
            return bst.model_to_string(), evals
        finally:
            os.environ.pop("LGBM_TPU_CHUNK", None)

    m_on, ev_on = run("32")
    m_off, ev_off = run("0")
    assert m_on == m_off
    # metric VALUES may differ from the legacy gate-off path by ~1 ulp of
    # score (docs/PERF.md: RF's running-mean renorm contracts differently
    # in the legacy eager ops); within the macro path they are exact
    np.testing.assert_allclose(
        ev_on["valid_0"]["binary_logloss"],
        ev_off["valid_0"]["binary_logloss"], rtol=1e-7)


def test_resume_from_checkpoint_mid_stream(tmp_path):
    """A checkpoint written mid-stream by a chunked run must resume to the
    byte-identical final model — under chunking AND per-iteration."""
    snap = str(tmp_path / "m.txt")

    def run(env, resume=None):
        os.environ["LGBM_TPU_CHUNK"] = env
        try:
            ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
            return lgb.train(
                {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                 "bagging_fraction": 0.7, "bagging_freq": 1},
                ds, num_boost_round=14, verbose_eval=False,
                snapshot_freq=5, snapshot_out=snap,
                resume_from=resume).model_to_string()
        finally:
            os.environ.pop("LGBM_TPU_CHUNK", None)

    full = run("32")
    resumed_chunked = run("32", resume=snap + ".ckpt")
    resumed_periter = run("0", resume=snap + ".ckpt")
    assert resumed_chunked == full
    assert resumed_periter == full


def test_metric_freq_gates_eval():
    """config.metric_freq (alias output_freq) was parsed but never read;
    the engine now evaluates every metric_freq-th iteration like the
    reference's OutputMetric loop."""
    ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
    vs = lgb.Dataset(XV, label=YV_BIN, reference=ds, free_raw_data=False)
    evals = {}
    lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": -1,
               "metric": "binary_logloss", "output_freq": 3},
              ds, num_boost_round=12, valid_sets=[vs],
              evals_result=evals, verbose_eval=False)
    assert len(evals["valid_0"]["binary_logloss"]) == 4


def test_early_stopping_without_valid_raises():
    """The init-time error moved up front (callbacks now skip no-eval
    iterations); training with early stopping but nothing to evaluate
    must still fail loudly."""
    ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
    with pytest.raises(ValueError, match="at least one dataset"):
        lgb.train({"objective": "binary", "num_leaves": 15,
                   "verbosity": -1, "metric": "None"},
                  ds, num_boost_round=5, early_stopping_rounds=2,
                  verbose_eval=False)


@pytest.mark.parametrize("params", [
    {"objective": "binary", "boosting": "dart", "num_leaves": 15},
    {"objective": "binary", "num_leaves": 15, "cegb_penalty_split": 0.1},
])
def test_c1_fallback_modes(params):
    """DART drop/rollback and CEGB bitmaps need per-iteration host logic:
    chunk_supported is False, update_chunk refuses, and engine training
    with the chunk gate ON still works through the c=1 path."""
    b = _booster(params, Y_BIN)
    assert not b.boosting.chunk_supported()
    with pytest.raises(RuntimeError, match="per-iteration"):
        b.update_chunk(4)
    os.environ["LGBM_TPU_CHUNK"] = "32"
    try:
        ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
        bst = lgb.train(dict(params, verbosity=-1), ds, num_boost_round=4,
                        verbose_eval=False)
        assert bst.current_iteration() == 4
    finally:
        os.environ.pop("LGBM_TPU_CHUNK", None)


def test_custom_fobj_not_chunk_supported():
    ds = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
    bst = lgb.train({"num_leaves": 15, "verbosity": -1}, ds,
                    num_boost_round=3, verbose_eval=False,
                    fobj=lambda preds, d: (
                        1.0 / (1.0 + np.exp(-preds)) - d.get_label(),
                        np.full(len(preds), 0.25)))
    assert bst.num_trees() == 3
    assert not bst.boosting.chunk_supported()


def test_chunk_stop_on_unsplittable():
    """A chunk whose early iteration produces no splittable leaves must
    truncate exactly like per-iteration training (constant labels stop
    at iteration 0 with the boost-from-average constant tree)."""
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
    y_const = np.full(N, 3.25)
    ds = lgb.Dataset(X, label=y_const, free_raw_data=False)
    b = lgb.Booster(params=params, train_set=ds)
    stopped = b.update_chunk(4)
    assert stopped
    assert b.current_iteration() == 0
    assert b.num_trees() == 1          # the constant AsConstantTree stump
    pred = b.predict(X[:5])
    np.testing.assert_allclose(pred, 3.25, rtol=1e-6)


def test_release_host_binned(monkeypatch):
    """free_raw_data + LGBM_TPU_FREE_BINNED=1 drops the host binned
    matrix after device upload; reuse fails with the informative error
    while prediction and training keep working."""
    monkeypatch.setenv("LGBM_TPU_FREE_BINNED", "1")
    ds = lgb.Dataset(X, label=Y_BIN)          # free_raw_data default True
    b = lgb.Booster(params={"objective": "binary", "num_leaves": 15,
                            "verbosity": -1}, train_set=ds)
    assert ds.binned is None
    for _ in range(3):
        b.update()
    assert b.num_trees() == 3
    assert np.isfinite(b.predict(X[:8])).all()
    with pytest.raises(RuntimeError, match="released"):
        lgb.Booster(params={"objective": "binary", "verbosity": -1},
                    train_set=ds)
    # free_raw_data=False keeps the host copy regardless
    ds2 = lgb.Dataset(X, label=Y_BIN, free_raw_data=False)
    lgb.Booster(params={"objective": "binary", "num_leaves": 15,
                        "verbosity": -1}, train_set=ds2)
    assert ds2.binned is not None


@pytest.mark.perf
def test_dispatch_probe_json():
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from dispatch_probe import run_probe
    out = run_probe(rows=4000, features=8, leaves=15, iters=4, chunks=(4,))
    assert out["dispatch_ms"] > 0
    assert out["per_iter"]["iters_per_sec"] > 0
    assert out["fused"]["4"]["iters_per_sec"] > 0
    assert "speedup_vs_per_iter" in out["fused"]["4"]
