"""Batched multi-booster training (lightgbm_tpu/multi/).

The hard contract: ``train_many`` vmaps the EXACT solo macro-chunk body
over a leading booster axis, so every extracted booster must be
BYTE-IDENTICAL in model text to the same config trained alone — across
modes (gbdt / bagging / GOSS / multiclass / quantized / lr schedules),
resident and 8-device data-parallel, through per-lane early stopping and
checkpoint bundles; ``cv(fused=True)`` must return the serial ``cv``'s
results dict bit-for-bit.  (Parity scope: the CPU test backend resolves
``hist_method=auto`` to the scatter family, whose accumulation is
order-invariant under vmap — docs/PERF.md "model axis".)
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.multi import expand_param_grid, group_boosters

pytestmark = pytest.mark.multi

RNG = np.random.RandomState(7)
N, F = 700, 10
X = RNG.randn(N, F)
Y_BIN = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.2 * RNG.randn(N) > 0).astype(float)
Y_MC = np.digitize(X[:, 0] + X[:, 1], [-0.5, 0.5]).astype(float)

XV = RNG.randn(300, F)
YV_BIN = (XV[:, 0] + 0.5 * XV[:, 1] * XV[:, 2] + 0.2 * RNG.randn(300) > 0).astype(float)

BASE = {"verbosity": -1, "num_leaves": 7, "learning_rate": 0.1}


def _lr_sched():
    return lgb.reset_parameter(learning_rate=lambda i: 0.2 * 0.95 ** i)


# mode -> (two structurally-identical lane configs varying only runtime
# fields, label, per-lane callback factories)
PARITY_CASES = {
    "gbdt": ([dict(BASE, objective="binary"),
              dict(BASE, objective="binary", learning_rate=0.23)],
             Y_BIN, None),
    "bagging": ([dict(BASE, objective="binary", bagging_fraction=0.7,
                      bagging_freq=2, bagging_seed=11),
                 dict(BASE, objective="binary", bagging_fraction=0.5,
                      bagging_freq=1, bagging_seed=3)],
                Y_BIN, None),
    "goss": ([dict(BASE, objective="binary", boosting="goss"),
              dict(BASE, objective="binary", boosting="goss",
                   learning_rate=0.3)],
             Y_BIN, None),
    "multiclass": ([dict(BASE, objective="multiclass", num_class=3),
                    dict(BASE, objective="multiclass", num_class=3,
                         learning_rate=0.2)],
                   Y_MC, None),
    "quant": ([dict(BASE, objective="binary", use_quantized_grad=True),
               dict(BASE, objective="binary", use_quantized_grad=True,
                    learning_rate=0.17)],
              Y_BIN, None),
    "lr_schedule": ([dict(BASE, objective="binary"),
                     dict(BASE, objective="binary")],
                    Y_BIN, _lr_sched),
}


def _ds(y=Y_BIN, x=X):
    return lgb.Dataset(x, label=y, free_raw_data=False)


def _solo(params, y, rounds=8, cb=None):
    return lgb.train(dict(params), _ds(y), num_boost_round=rounds,
                     verbose_eval=False,
                     callbacks=[cb()] if cb else None).model_to_string()


# the full 6-case resident matrix runs in tier-1; the data-parallel arm
# compiles shard_map x vmap programs per case, so one representative
# (gbdt) stays fast and the rest ride the slow marker (-m multi runs all)
_MATRIX = []
for _c in sorted(PARITY_CASES):
    _MATRIX.append(pytest.param(_c, False, id=f"{_c}-resident"))
    _MATRIX.append(pytest.param(
        _c, True, id=f"{_c}-data_parallel",
        marks=() if _c == "gbdt" else (pytest.mark.slow,)))


@pytest.mark.parametrize("case,sharded", _MATRIX)
def test_train_many_matches_solo(case, sharded):
    params_list, y, cb = PARITY_CASES[case]
    if sharded:
        import jax
        if jax.device_count() < 2:
            pytest.skip("needs >= 2 devices")
        params_list = [dict(p, tree_learner="data") for p in params_list]
    solos = [_solo(p, y, cb=cb) for p in params_list]
    many = lgb.train_many(
        [dict(p) for p in params_list], _ds(y), num_boost_round=8,
        callbacks=[[cb()] for _ in params_list] if cb else None)
    for i, bst in enumerate(many):
        assert bst.model_to_string() == solos[i], \
            f"{case} lane {i}: batched != solo"


def test_heterogeneous_configs_one_call():
    """Structurally-different configs in ONE call cross group boundaries
    (binary vs GOSS vs multiclass-on-other-labels can't share a trace)
    yet each lane still lands byte-identical."""
    p0 = dict(BASE, objective="binary")
    p1 = dict(BASE, objective="binary", boosting="goss", num_leaves=15)
    p2 = dict(BASE, objective="binary", bagging_fraction=0.6,
              bagging_freq=1)
    solos = [_solo(p, Y_BIN) for p in (p0, p1, p2)]
    many = lgb.train_many([dict(p0), dict(p1), dict(p2)], _ds(),
                          num_boost_round=8)
    assert [b.model_to_string() for b in many] == solos


def test_per_lane_round_budgets():
    """A lane whose num_iterations ends mid-batch freezes (inert inputs,
    no retrace) while its neighbours train on."""
    p_short = dict(BASE, objective="binary", num_iterations=5)
    p_long = dict(BASE, objective="binary", learning_rate=0.2)
    solo_short = _solo(p_short, Y_BIN, rounds=11)
    solo_long = _solo(p_long, Y_BIN, rounds=11)
    many = lgb.train_many([dict(p_short), dict(p_long)], _ds(),
                          num_boost_round=11)
    assert many[0].current_iteration() == 5
    assert many[0].model_to_string() == solo_short
    assert many[1].current_iteration() == 11
    assert many[1].model_to_string() == solo_long


def test_early_stopping_mid_batch():
    """One lane early-stops (best_iteration, truncated eval history and
    all) while the other lane's bytes are untouched."""
    vs = lgb.Dataset(XV, label=YV_BIN, free_raw_data=False)
    p_es = dict(BASE, objective="binary", metric="binary_logloss")
    p_go = dict(BASE, objective="binary", metric="binary_logloss",
                learning_rate=0.02)
    er_solo = {}
    solo = lgb.train(dict(p_es), _ds(), num_boost_round=30,
                     valid_sets=[vs], early_stopping_rounds=2,
                     evals_result=er_solo, verbose_eval=False)
    solo_go = lgb.train(dict(p_go), _ds(), num_boost_round=30,
                        valid_sets=[vs], early_stopping_rounds=2,
                        verbose_eval=False)
    er_many = [{}, {}]
    many = lgb.train_many([dict(p_es), dict(p_go)], _ds(),
                          num_boost_round=30, valid_sets=[vs],
                          early_stopping_rounds=2, evals_results=er_many)
    assert many[0].model_to_string() == solo.model_to_string()
    assert many[0].best_iteration == solo.best_iteration
    assert er_many[0] == er_solo
    assert many[1].model_to_string() == solo_go.model_to_string()


def test_cv_fused_matches_serial():
    params = dict(BASE, objective="binary", metric="binary_logloss")
    r_serial = lgb.cv(dict(params), _ds(), num_boost_round=8, nfold=3,
                      stratified=False, shuffle=False, verbose_eval=False)
    r_fused = lgb.cv(dict(params), _ds(), num_boost_round=8, nfold=3,
                     stratified=False, shuffle=False, verbose_eval=False,
                     fused=True)
    assert sorted(r_serial) == sorted(r_fused)
    for k in r_serial:
        assert r_serial[k] == r_fused[k], f"cv key {k} diverged"


def test_cv_fused_custom_fobj_falls_back():
    """A custom fobj is not chunk-supported; fused cv must quietly run
    the serial path and return identical results."""

    def fobj(preds, ds):
        lab = ds.get_label()
        p = 1.0 / (1.0 + np.exp(-preds))
        return p - lab, p * (1.0 - p)

    params = dict(BASE, objective="binary", metric="binary_logloss")
    kw = dict(num_boost_round=6, nfold=3, stratified=False, shuffle=False,
              verbose_eval=False, fobj=fobj)
    r_serial = lgb.cv(dict(params), _ds(), **kw)
    r_fused = lgb.cv(dict(params), _ds(), fused=True, **kw)
    assert {k: r_serial[k] for k in r_serial} == \
        {k: r_fused[k] for k in r_fused}


def test_checkpoint_from_batched_run_resumes(tmp_path):
    """A bundle snapshotted mid-batch carries the full solo training
    state, so solo ``train(resume_from=...)`` finishes the run to the
    byte-identical model."""
    p0 = dict(BASE, objective="binary", bagging_fraction=0.7,
              bagging_freq=1)
    p1 = dict(BASE, objective="binary", learning_rate=0.25)
    full = [_solo(p0, Y_BIN, rounds=14), _solo(p1, Y_BIN, rounds=14)]
    snaps = [str(tmp_path / "lane0.txt"), str(tmp_path / "lane1.txt")]
    many = lgb.train_many([dict(p0), dict(p1)], _ds(), num_boost_round=14,
                          snapshot_freq=5, snapshot_outs=snaps)
    assert [b.model_to_string() for b in many] == full
    for p, snap, want in zip((p0, p1), snaps, full):
        resumed = lgb.train(dict(p), _ds(), num_boost_round=14,
                            verbose_eval=False,
                            resume_from=snap + ".ckpt").model_to_string()
        assert resumed == want


def test_expand_param_grid():
    grid = {"objective": "binary", "learning_rate": [0.1, 0.2],
            "num_leaves": [7, 15], "verbosity": -1}
    cfgs = expand_param_grid(grid)
    assert len(cfgs) == 4
    assert sorted((c["learning_rate"], c["num_leaves"]) for c in cfgs) == \
        [(0.1, 7), (0.1, 15), (0.2, 7), (0.2, 15)]
    assert all(c["objective"] == "binary" for c in cfgs)


def test_train_many_grid_dict_matches_solo():
    grid = {"objective": "binary", "verbosity": -1, "num_leaves": 7,
            "learning_rate": [0.1, 0.3]}
    many = lgb.train_many(grid, _ds(), num_boost_round=6)
    for lr, bst in zip((0.1, 0.3), many):
        assert bst.model_to_string() == _solo(
            {"objective": "binary", "verbosity": -1, "num_leaves": 7,
             "learning_rate": lr}, Y_BIN, rounds=6)


def test_structural_grouping():
    """Runtime-varying fields share a trace; structural fields do not;
    chunk-unsupported modes fall to singleton solo groups."""
    shared = _ds()      # shared mode keys on the Dataset's identity
    mk = lambda p: lgb.Booster(params=dict(p, verbosity=-1),
                               train_set=shared).boosting
    b_lr1 = mk(dict(BASE, objective="binary"))
    b_lr2 = mk(dict(BASE, objective="binary", learning_rate=0.3,
                    bagging_fraction=0.5, bagging_freq=1))
    b_leaves = mk(dict(BASE, objective="binary", num_leaves=15))
    b_dart = mk(dict(BASE, objective="binary", boosting="dart"))
    groups = group_boosters([b_lr1, b_lr2, b_leaves, b_dart],
                            stacked=False)
    sizes = sorted(len(g.boosters) for g in groups)
    assert sizes == [1, 1, 2]
    batched = [g for g in groups if len(g.boosters) == 2][0]
    assert batched.key is not None
    assert {id(b) for b in batched.boosters} == {id(b_lr1), id(b_lr2)}
    dart_group = [g for g in groups if g.boosters[0] is b_dart][0]
    assert dart_group.key is None       # solo path, never vmapped


def test_plan_model_batch_budget_degrades():
    from lightgbm_tpu.ops.planner import plan_model_batch
    roomy = plan_model_batch(b_total=8, rows=200_000, features=28,
                             num_bins=64, num_leaves=31,
                             budget_bytes=1 << 34)
    assert roomy.b_chunk == 8 and roomy.num_dispatch_groups == 1
    assert not roomy.degraded
    tight = plan_model_batch(b_total=8, rows=200_000, features=28,
                             num_bins=64, num_leaves=31,
                             budget_bytes=3 * roomy.per_lane_bytes
                             + roomy.shared_bytes)
    assert 1 <= tight.b_chunk < 8
    assert tight.degraded
    assert tight.num_dispatch_groups == -(-8 // tight.b_chunk)
    assert tight.predicted_peak_bytes <= tight.budget_bytes


def test_plan_model_batch_env_override(monkeypatch):
    from lightgbm_tpu.ops.planner import plan_model_batch
    monkeypatch.setenv("LGBM_TPU_MODEL_BATCH", "2")
    plan = plan_model_batch(b_total=8, rows=10_000, features=10,
                            num_bins=64, budget_bytes=1 << 34)
    assert plan.b_chunk == 2 and plan.forced
    monkeypatch.setenv("LGBM_TPU_MODEL_BATCH", "off")
    plan = plan_model_batch(b_total=8, rows=10_000, features=10,
                            num_bins=64, budget_bytes=1 << 34)
    assert plan.b_chunk == 1    # sequential: solo dispatch per booster


def test_model_batch_env_caps_grouping(monkeypatch):
    """LGBM_TPU_MODEL_BATCH=0 must force the solo path end-to-end and
    still produce identical bytes (the degradation arm is not a second
    implementation)."""
    monkeypatch.setenv("LGBM_TPU_MODEL_BATCH", "0")
    p0 = dict(BASE, objective="binary")
    p1 = dict(BASE, objective="binary", learning_rate=0.3)
    many = lgb.train_many([dict(p0), dict(p1)], _ds(), num_boost_round=6)
    monkeypatch.delenv("LGBM_TPU_MODEL_BATCH")
    assert [b.model_to_string() for b in many] == \
        [_solo(p0, Y_BIN, rounds=6), _solo(p1, Y_BIN, rounds=6)]


def test_refresh_many_matches_serial_candidates(tmp_path):
    """Stacked mode: a per-segment family warm-starts from its deployed
    models in one call, each candidate byte-identical to its solo
    train_candidate run."""
    from lightgbm_tpu.lifecycle.refresh import (fresh_dataset,
                                                refresh_many,
                                                train_candidate)
    params = [dict(BASE, objective="binary"),
              dict(BASE, objective="binary", learning_rate=0.2)]
    seg_x = [RNG.randn(500, F), RNG.randn(640, F)]
    seg_y = [(x[:, 0] + 0.3 * x[:, 1] > 0).astype(float) for x in seg_x]
    fresh_x = [x + 0.01 * np.random.RandomState(9).randn(*x.shape)
               for x in seg_x]
    deployed = []
    for p, x, y in zip(params, seg_x, seg_y):
        deployed.append(lgb.train(
            dict(p), lgb.Dataset(x, label=y, free_raw_data=False),
            num_boost_round=5, verbose_eval=False))

    def _fresh_sets():
        return [fresh_dataset(
            lgb.Dataset(x, label=y, free_raw_data=False), fx, y)
            for x, y, fx in zip(seg_x, seg_y, fresh_x)]

    solos = [train_candidate(d, t, dict(p), 6).model_to_string()
             for d, t, p in zip(deployed, _fresh_sets(), params)]
    cands = refresh_many(deployed, _fresh_sets(), params, 6)
    assert [c.model_to_string() for c in cands] == solos


def test_sweep_probe_reports():
    import sys
    sys.path.insert(0, str(__import__("pathlib").Path(
        __file__).resolve().parents[1] / "tools"))
    from sweep_probe import run_probe
    out = run_probe(rows=2000, features=6, max_bin=15, leaves=7,
                    chunk=2, reps=1, widths=(1, 2))
    for B in (1, 2):
        assert out[f"B{B}"]["iters_per_sec"] > 0
    assert out["model_batch_plan"]["b_total"] == 2
    assert out["aggregate_speedup_vs_b1"] > 0
    assert "accel" in out


@pytest.mark.obs
def test_devprof_batched_row():
    from lightgbm_tpu.obs.devprof import histogram_utilization_table
    t = histogram_utilization_table(rows=1500, features=4, num_bins=8,
                                    reps=1, quant=False)
    row = t["f32/scatter_batched8/untiled"]
    assert "error" not in row
    assert row["seconds_per_call"] > 0


@pytest.mark.fleet
def test_fleet_swaps_sweep_winner():
    """The sweep winner hot-swaps into a serving Fleet through the
    probe-quarantine path and serves its exact raw scores."""
    from lightgbm_tpu.fleet import Fleet
    vs = lgb.Dataset(XV, label=YV_BIN, free_raw_data=False)
    evals = [{}, {}, {}]
    grid = {"objective": "binary", "verbosity": -1, "num_leaves": 7,
            "metric": "binary_logloss", "learning_rate": [0.05, 0.1, 0.2]}
    many = lgb.train_many(grid, _ds(), num_boost_round=8,
                          valid_sets=[vs], evals_results=evals)
    winner = min(
        range(3), key=lambda i: evals[i]["valid_0"]["binary_logloss"][-1])
    fleet = Fleet(max_batch_rows=128)
    fleet.config.deadline_classes["interactive"] = 10_000.0
    try:
        fleet.add_model("seg", many[(winner + 1) % 3], weight=1.0)
        fleet.swap_model("seg", many[winner])   # probe-quarantine path
        q = np.asarray(XV[:16], np.float32)
        assert np.array_equal(
            fleet.predict("seg", q, timeout=60),
            many[winner].predict(q, raw_score=True))
    finally:
        fleet.close()
