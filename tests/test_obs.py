"""Observability plane (lightgbm_tpu/obs/, docs/OBSERVABILITY.md):
structured tracing, the unified metrics registry + Prometheus exposition,
measured device profiling, and the timer satellite features.

The tracing layer's acceptance bar (ISSUE 6): spans nest and close
correctly under exceptions, the disabled path is a shared null context
manager (no allocation, no events), the Chrome-trace JSON validates
(timestamp-sorted, pid/tid on every event), and allgather-retry /
checkpoint spans appear in a chaos-injected run.
"""

import json
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.metrics import MetricsRegistry, global_registry
from lightgbm_tpu.obs.trace import (Tracer, _NULL_SPAN, global_tracer,
                                    span, span_coverage)
from lightgbm_tpu.utils.timer import Timer, global_timer

pytestmark = pytest.mark.obs


# -------------------------------------------------------------- trace core


def test_spans_record_and_nest():
    t = Tracer(enabled=True)
    with t.span("outer", kind="test"):
        with t.span("inner"):
            pass
    evs = t.events()
    assert [e["name"] for e in evs] == ["inner", "outer"]
    inner, outer = evs
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    for e in evs:
        assert e["ph"] == "X" and "pid" in e and "tid" in e
    assert outer["args"]["kind"] == "test"


def test_span_closes_under_exception():
    t = Tracer(enabled=True)
    with pytest.raises(ValueError):
        with t.span("outer"):
            with t.span("boom"):
                raise ValueError("x")
    evs = {e["name"]: e for e in t.events()}
    # BOTH spans closed despite the raise, tagged with the error type
    assert set(evs) == {"outer", "boom"}
    assert evs["boom"]["args"]["error"] == "ValueError"
    assert evs["outer"]["args"]["error"] == "ValueError"


def test_disabled_mode_is_shared_null_span():
    t = Tracer(enabled=False)
    cm = t.span("x", a=1)
    assert cm is _NULL_SPAN          # no per-call allocation when disabled
    with cm:
        pass
    t.instant("y")
    assert t.events() == []
    # the module-level helper takes the same fast path
    was = global_tracer.enabled
    global_tracer.disable()
    try:
        assert span("z") is _NULL_SPAN
    finally:
        global_tracer.enabled = was


def test_chrome_trace_json_validates():
    t = Tracer(enabled=True)

    def worker():
        with t.span("thread_span"):
            pass

    th = threading.Thread(target=worker)
    with t.span("main_span"):
        th.start()
        th.join()
    t.instant("marker", note=1)
    doc = json.loads(json.dumps(t.to_chrome_trace()))
    evs = doc["traceEvents"]
    assert len(evs) == 4              # metadata + 2 spans + 1 instant
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)           # timestamp-sorted
    for e in evs:
        assert "pid" in e and "tid" in e and "ts" in e
        assert e["ph"] in ("X", "i", "M")
    tids = {e["tid"] for e in evs if e["ph"] == "X"}
    assert len(tids) == 2             # two threads visible


def test_dump_and_coverage(tmp_path):
    t = Tracer(enabled=True)
    import time
    with t.span("root"):
        with t.span("a"):
            time.sleep(0.02)
        with t.span("b"):
            time.sleep(0.02)
    cov = span_coverage(t.events(), "root")
    assert cov is not None and cov > 0.9
    p = t.dump(str(tmp_path / "trace.json"))
    with open(p) as fh:
        assert "traceEvents" in json.load(fh)


def test_training_emits_spans_and_registry_instruments():
    global_tracer.reset()
    global_tracer.enable()
    try:
        rng = np.random.RandomState(0)
        X = rng.rand(500, 4)
        y = (X[:, 0] > 0.5).astype(np.float32)
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=3)
        names = {e["name"] for e in global_tracer.events()}
        assert "engine.train" in names
        assert "engine.step" in names
        assert "planner.plan" in names
        # dispatch happens through the fused chunk program by default
        assert names & {"macro.dispatch", "gbdt.dispatch"}
        assert names & {"macro.host_fetch", "gbdt.finish_iter"}
        cov = span_coverage(global_tracer.events(), "engine.train")
        assert cov is not None and cov > 0.9
    finally:
        global_tracer.disable()
        global_tracer.reset()
    d = global_registry.to_dict()
    assert d["counters"].get("train_iterations_total", 0) >= 3
    assert "train_hist_method" in d["gauges"]
    assert d["gauges"]["train_hist_method"] != "auto"
    assert "train_tile_rows" in d["gauges"]
    assert d["gauges"].get("train_hist_predicted_peak_bytes", 0) > 0


def test_training_disabled_trace_stays_empty():
    global_tracer.reset()
    assert not global_tracer.enabled
    rng = np.random.RandomState(0)
    X = rng.rand(300, 4)
    y = (X[:, 0] > 0.5).astype(np.float32)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
              lgb.Dataset(X, label=y), num_boost_round=2)
    assert global_tracer.events() == []


# ----------------------------------------------- chaos-injected span tests


@pytest.mark.chaos
def test_allgather_retry_spans_under_chaos():
    """An injected transport fault must surface as retried
    ``allgather.attempt`` spans (attempt 0 not committed, a later attempt
    committed) on top of the existing retry/recover behavior."""
    from lightgbm_tpu.parallel.dist_data import make_fake_allgather
    from lightgbm_tpu.resilience import (ChaosRegistry, ResilienceConfig,
                                         resilient_allgather)

    world = 4
    cfg = ResilienceConfig(deadline_s=20.0, max_retries=5,
                           base_backoff_s=0.01)
    chaos = ChaosRegistry("allgather.bitflip@0:rank=1", seed=0)
    fake = make_fake_allgather(world, timeout=2.0)
    global_tracer.reset()
    global_tracer.enable()
    try:
        out, errs = [None] * world, [None] * world

        def runner(k):
            try:
                ag = chaos.wrap_allgather(fake(k), k)
                out[k] = resilient_allgather(
                    f"rank{k}".encode(), ag, world=world, rank=k,
                    config=cfg)
            except Exception as e:  # noqa: BLE001
                errs[k] = e

        threads = [threading.Thread(target=runner, args=(k,))
                   for k in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert errs == [None] * world
        atts = [e for e in global_tracer.events()
                if e["name"] == "allgather.attempt"]
        assert atts, "no allgather.attempt spans recorded"
        assert any(not a["args"]["committed"] for a in atts), \
            "the injected fault never produced a failed attempt span"
        assert any(a["args"]["committed"] and a["args"]["attempt"] >= 1
                   for a in atts), "no recovered-retry span"
    finally:
        global_tracer.disable()
        global_tracer.reset()


@pytest.mark.chaos
def test_checkpoint_spans_appear(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.rand(400, 4)
    y = (X[:, 0] > 0.5).astype(np.float32)
    P = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    global_tracer.reset()
    global_tracer.enable()
    try:
        lgb.train(P, lgb.Dataset(X, label=y), 4, verbose_eval=False,
                  snapshot_freq=2, snapshot_out=str(tmp_path / "m.txt"))
        lgb.train(P, lgb.Dataset(X, label=y), 4, verbose_eval=False,
                  resume_from=str(tmp_path / "m.txt.ckpt"))
        names = [e["name"] for e in global_tracer.events()]
        assert "checkpoint.save" in names
        assert "checkpoint.load" in names
    finally:
        global_tracer.disable()
        global_tracer.reset()
    d = global_registry.to_dict()
    assert d["histograms"]["checkpoint_save_ms"]["count"] >= 2
    assert d["histograms"]["checkpoint_load_ms"]["count"] >= 1


# ------------------------------------------------- unified metrics registry


def test_serving_metrics_shim_is_the_obs_registry():
    """Back-compat satellite: the historical import path and to_dict key
    layout survive the move to obs/ unchanged."""
    from lightgbm_tpu.serving.metrics import (LATENCY_BUCKETS_MS,
                                              MetricsRegistry as ShimReg)
    assert ShimReg is MetricsRegistry
    assert LATENCY_BUCKETS_MS[-1] == float("inf")
    r = ShimReg()
    r.counter("requests_total").inc(2)
    r.gauge("queue_depth_rows").set(5)
    r.histogram("request_latency_ms").observe(3.0)
    d = r.to_dict()
    # EXACT historical layout: three sections, no extras without children
    assert sorted(d.keys()) == ["counters", "gauges", "histograms"]
    assert d["counters"] == {"requests_total": 2}
    assert d["gauges"] == {"queue_depth_rows": 5}
    h = d["histograms"]["request_latency_ms"]
    assert h["count"] == 1 and h["buckets"] == {"5.0": 1}
    json.loads(r.dump_json())


def test_registry_components():
    root = MetricsRegistry()
    child = MetricsRegistry()
    child.counter("x").inc()
    name = root.attach_child("serving", child)
    assert name == "serving"
    name2 = root.attach_child("serving", MetricsRegistry())
    assert name2 == "serving_2"        # unique names, no clobber
    d = root.to_dict()
    assert d["components"]["serving"]["counters"]["x"] == 1
    root.detach_child(name)
    root.detach_child(name2)
    assert "components" not in root.to_dict()


def test_prometheus_exposition():
    r = MetricsRegistry()
    r.counter("requests_total").inc(7)
    r.gauge("queue_depth").set(3)
    r.gauge("active_model_digest").set("abc123")
    h = r.histogram("latency_ms", buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5.0)
    h.observe(100.0)
    child = MetricsRegistry()
    child.counter("hits").inc()
    r.attach_child("serving", child)
    text = r.to_prometheus(prefix="lgbt")
    assert "# TYPE lgbt_requests_total counter\nlgbt_requests_total 7" in text
    assert "lgbt_queue_depth 3" in text
    assert 'lgbt_active_model_digest_info{value="abc123"} 1' in text
    # cumulative buckets + +Inf + sum/count
    assert 'lgbt_latency_ms_bucket{le="1.0"} 1' in text
    assert 'lgbt_latency_ms_bucket{le="10.0"} 2' in text
    assert 'lgbt_latency_ms_bucket{le="+Inf"} 3' in text
    assert "lgbt_latency_ms_count 3" in text
    assert "lgbt_serving_hits 1" in text
    # every sample line ends in a parseable number
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        float(line.rsplit(" ", 1)[1])


def test_server_joins_process_registry_and_prometheus():
    rng = np.random.RandomState(0)
    X = rng.rand(300, 5)
    y = (X[:, 0] > 0.5).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 3)
    srv = bst.serve(max_batch_rows=64, backend="host")
    try:
        srv.predict(X[:16])
        comp = global_registry.to_dict().get("components", {})
        assert any(k.startswith("serving") for k in comp)
        text = srv.prometheus_text()
        assert "lgbt_serving_requests_total 1" in text
    finally:
        srv.close()
    comp = global_registry.to_dict().get("components", {})
    assert not any(v is srv.metrics for v in comp.values())


# ------------------------------------------------------------ timer bridge


def test_timer_json_dump(tmp_path):
    t = Timer(enabled=True)
    with t.section("A::B"):
        pass
    with t.section("A::B"):
        pass
    d = t.to_dict()
    assert d["A::B"]["calls"] == 2 and d["A::B"]["total_s"] >= 0
    p = tmp_path / "timers.json"
    s = t.dump_json(str(p))
    loaded = json.loads(p.read_text())
    assert loaded == json.loads(s)
    assert loaded["timers"]["A::B"]["calls"] == 2


def test_timer_env_json_mode(tmp_path, monkeypatch):
    """LIGHTGBM_TPU_TIMETAG=json:<path> writes machine-readable totals at
    exit (satellite: no stderr scraping)."""
    out = tmp_path / "t.json"
    monkeypatch.setenv("LIGHTGBM_TPU_TIMETAG", f"json:{out}")
    from lightgbm_tpu.utils import timer as timer_mod
    assert Timer().enabled        # "json:..." counts as enabled
    was = global_timer.enabled
    global_timer.enable()
    try:
        with global_timer.section("ExitDump::Test"):
            pass
        timer_mod._print_at_exit()
    finally:
        global_timer.enabled = was
    loaded = json.loads(out.read_text())
    assert "ExitDump::Test" in loaded["timers"]


def test_timer_publish_mirrors_registry():
    t = Timer(enabled=True)
    with t.section("Pub::X"):
        pass
    reg = MetricsRegistry()
    t.publish(reg)
    g = reg.to_dict()["gauges"]
    assert g["timer.Pub::X.calls"] == 1
    assert g["timer.Pub::X.total_s"] >= 0


# ---------------------------------------------------------------- devprof


def test_devprof_measures_a_program():
    import jax.numpy as jnp

    from lightgbm_tpu.obs.devprof import measure_program, program_cost

    a = jnp.ones((128, 128), jnp.float32)

    def f(x):
        return x @ x

    m = measure_program(f, (a,), reps=1)
    assert m["seconds_per_call"] > 0
    assert m["peak_flops"] > 0 and m["peak_hbm_bw"] > 0
    cost = program_cost(f, a)
    if not cost:        # backend without a cost model: degrade, not fail
        pytest.skip("cost_analysis unavailable on this backend")
    assert cost["flops"] > 0
    assert m["mfu"] > 0


def test_devprof_histogram_table_small():
    from lightgbm_tpu.obs.devprof import histogram_utilization_table

    t = histogram_utilization_table(rows=2000, features=6, num_bins=16,
                                    slots=4, reps=1, quant=True)
    keys = [k for k in t if "/" in k]
    # the full family x {f32, quant} x {untiled, tiled}, incl. the
    # Pallas rows (bin-only VPU kernel + fused megakernel), the 8-lane
    # model-axis row (f32/scatter_batched8) and the collective-seam
    # rows (accumulate → {flat, hierarchical} reduce → sibling scan)
    assert len(keys) == 34
    for fam in ("f32/pallas", "f32/fused", "quant/fused",
                "f32/scatter_batched8", "f32/fused_sharded_flat",
                "f32/fused_sharded_hier", "quant/fused_sharded_flat",
                "quant/fused_sharded_hier"):
        assert f"{fam}/untiled" in t and f"{fam}/tiled" in t
    for k in keys:
        v = t[k]
        assert "error" in v or v["seconds_per_call"] > 0, (k, v)
    timed = [k for k in keys if "error" not in t[k]]
    assert timed, "every variant errored"
    # the fused rows must actually measure (interpret mode on CPU), not
    # error out — they are the bench's acceptance figure
    assert "seconds_per_call" in t["f32/fused/untiled"], t["f32/fused/untiled"]
    assert "seconds_per_call" in t["quant/fused/tiled"], t["quant/fused/tiled"]


def test_obs_dump_tool(tmp_path):
    import os
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    from obs_dump import run_dump

    r = run_dump(out_dir=str(tmp_path), rows=2000, features=6, trees=3,
                 leaves=7)
    assert r["trace_events"] > 0
    assert r["train_coverage"] > 0.9
    assert "checkpoint.save" in r["span_names"]
    assert "serving.dispatch" in r["span_names"]
    trace = json.loads((tmp_path / "obs_trace.json").read_text())
    assert trace["traceEvents"]
    snap = json.loads((tmp_path / "obs_metrics.json").read_text())
    assert "counters" in snap and "gauges" in snap
    # the serving component must be IN the snapshot (dumped before close
    # detaches it) — the whole point of the unified registry
    assert any(k.startswith("serving") for k in snap.get("components", {}))
    prom = (tmp_path / "obs_metrics.prom").read_text()
    assert "# TYPE" in prom
    # the dump restored the disabled-by-default state
    assert not global_tracer.enabled or os.environ.get(
        "LIGHTGBM_TPU_TRACE")


def test_bench_mfu_estimate_guards_zero_peak():
    """Satellite: bench.py's MFU estimate must not divide by an unknown
    device's zero peak."""
    import os
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    import bench

    assert bench.mfu_estimate(1000, 28, 63, 255, 0.5, 0.0) == 0.0
    assert bench.mfu_estimate(1000, 28, 63, 255, 0.5, -1.0) == 0.0
    assert bench.mfu_estimate(1000, 28, 63, 255, 0.5, 197e12) > 0.0
