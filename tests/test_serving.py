"""Serving subsystem: micro-batched, shape-bucketed inference
(lightgbm_tpu/serving/) — concurrency bit-equality, bucket reuse,
hot-swap under load, deadline/backpressure rejection, graceful drain.

All CPU-runnable under the tier-1 command (conftest forces the CPU
backend); data is generated float32-precise so the "device" backend's
routing-exactness domain applies and serving output must be BIT-equal to
``StackedForest.predict_raw``.
"""

import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (BucketLadder, DeadlineExceeded, QueueFull,
                                  ServerClosed, ServingError)

F = 10


def _f32_data(rng, n, f=F):
    """float64 data whose values are exactly float32-representable."""
    return rng.randn(n, f).astype(np.float32).astype(np.float64)


def _train(n=1500, rounds=12, leaves=15, seed=0, num_class=None):
    rng = np.random.RandomState(seed)
    X = _f32_data(rng, n)
    params = {"objective": "binary", "verbosity": -1, "num_leaves": leaves}
    if num_class:
        params.update({"objective": "multiclass", "num_class": num_class})
        y = rng.randint(0, num_class, n).astype(float)
    else:
        y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=rounds,
                    verbose_eval=False)
    return bst


@pytest.fixture(scope="module")
def binary_booster():
    return _train()


# ------------------------------------------------------------ bucket ladder


def test_bucket_ladder():
    lad = BucketLadder(8, 1024)
    assert lad.buckets == [8, 16, 32, 64, 128, 256, 512, 1024]
    assert lad.bucket_for(1) == 8
    assert lad.bucket_for(8) == 8
    assert lad.bucket_for(9) == 16
    assert lad.bucket_for(1024) == 1024
    with pytest.raises(ValueError):
        lad.bucket_for(1025)
    # non-power-of-two bounds round up
    assert BucketLadder(6, 100).buckets == [8, 16, 32, 64, 128]


# --------------------------------------------- concurrency + bit-equality


@pytest.mark.parametrize("backend", ["device", "host"])
def test_concurrent_mixed_sizes_bit_equal(binary_booster, backend):
    """N threads x mixed request sizes through the server == direct
    StackedForest.predict_raw, bitwise; batches mix submitters."""
    bst = binary_booster
    sf = bst._forest(0, 12)
    srv = bst.serve(max_batch_rows=256, batch_window_ms=2.0,
                    backend=backend)
    mismatches = []

    def worker(seed):
        r = np.random.RandomState(seed)
        for _ in range(8):
            m = int(r.randint(1, 400))        # spans buckets AND splits
            Xr = _f32_data(r, m)
            out = srv.predict(Xr, timeout=30)
            ref = sf.predict_raw(Xr)[0]
            if not np.array_equal(out, ref):
                mismatches.append((seed, m))

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    md = srv.metrics_dict()
    srv.close()
    assert mismatches == []
    c = md["counters"]
    assert c["requests_completed"] == 48
    # the acceptance bar: at least one batch coalesced >= 2 submitters
    assert c.get("multi_submitter_batches", 0) >= 1
    assert md["histograms"]["batch_fill_ratio"]["count"] == c["batches_total"]


def test_multiclass_and_transform(binary_booster):
    bst = _train(num_class=3, rounds=6, seed=2)
    sf = bst._forest(0, 6)
    rng = np.random.RandomState(5)
    Xq = _f32_data(rng, 70)
    with bst.serve(max_batch_rows=128) as srv:
        out = srv.predict(Xq)
        assert out.shape == (70, 3)
        assert np.array_equal(out, sf.predict_raw(Xq, num_class=3).T)
    # raw_score=False matches Booster.predict's transformed output
    with binary_booster.serve(max_batch_rows=128, raw_score=False) as srv:
        got = srv.predict(Xq)
        np.testing.assert_array_equal(got, binary_booster.predict(Xq))


def test_rf_average_output_raw_scaling(binary_booster):
    """raw_score=True must match Booster.predict(raw_score=True), which
    for average_output (rf) models divides by the iteration count."""
    rng = np.random.RandomState(17)
    X = _f32_data(rng, 1200)
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    rf = lgb.train(
        {"objective": "binary", "verbosity": -1, "num_leaves": 15,
         "boosting": "rf", "bagging_fraction": 0.8, "bagging_freq": 1},
        lgb.Dataset(X, label=y), num_boost_round=6, verbose_eval=False)
    assert rf.average_output
    Xq = _f32_data(rng, 40)
    with rf.serve(max_batch_rows=64) as srv:
        np.testing.assert_array_equal(srv.predict(Xq),
                                      rf.predict(Xq, raw_score=True))
    with rf.serve(max_batch_rows=64, raw_score=False) as srv:
        np.testing.assert_array_equal(srv.predict(Xq), rf.predict(Xq))


def test_single_row_and_empty(binary_booster):
    sf = binary_booster._forest(0, 12)
    rng = np.random.RandomState(9)
    with binary_booster.serve(max_batch_rows=64) as srv:
        x1 = _f32_data(rng, 1)[0]            # 1-D input, single row
        assert np.array_equal(srv.predict(x1), sf.predict_raw(x1[None])[0])
        out = srv.predict(np.zeros((0, F)))
        assert out.shape == (0,)
        with pytest.raises(ServingError):
            srv.predict(np.zeros((3, F + 2)))   # feature-count mismatch


# ------------------------------------------------------------ bucket reuse


def test_bucket_reuse_no_recompile(binary_booster):
    """Repeat shapes must hit the program registry: the compile counter
    freezes after warmup while the hit counter keeps climbing."""
    rng = np.random.RandomState(3)
    srv = binary_booster.serve(max_batch_rows=256, batch_window_ms=0.5)
    sizes = [5, 20, 70, 200]
    for m in sizes:                           # warmup: one compile per bucket
        srv.predict(_f32_data(rng, m))
    compiles_after_warmup = srv.metrics_dict()["counters"]["compile_events"]
    assert compiles_after_warmup <= len(sizes)
    for _ in range(3):
        for m in sizes:
            srv.predict(_f32_data(rng, m))
    md = srv.metrics_dict()
    srv.close()
    assert md["counters"]["compile_events"] == compiles_after_warmup
    assert md["counters"]["bucket_hits"] >= 3 * len(sizes)


# ---------------------------------------------------------------- hot swap


def test_hot_swap_under_load(binary_booster):
    """Swap the serving model while traffic flows: no dropped or failed
    requests, every result bit-matches either the old or the new model,
    and post-swap results match the new model."""
    b1 = binary_booster
    b2 = _train(rounds=9, leaves=7, seed=4)
    sf1, sf2 = b1._forest(0, 12), b2._forest(0, 9)
    srv = b1.serve(max_batch_rows=128, batch_window_ms=1.0)
    stop = threading.Event()
    bad = []

    def load(seed):
        r = np.random.RandomState(seed)
        while not stop.is_set():
            Xr = _f32_data(r, int(r.randint(1, 100)))
            out = srv.predict(Xr, timeout=30)
            if not (np.array_equal(out, sf1.predict_raw(Xr)[0])
                    or np.array_equal(out, sf2.predict_raw(Xr)[0])):
                bad.append(len(Xr))

    threads = [threading.Thread(target=load, args=(s,)) for s in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    srv.swap_model(b2, warm=True, block=True)
    time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join()
    rng = np.random.RandomState(11)
    Xq = _f32_data(rng, 40)
    post = srv.predict(Xq)
    md = srv.metrics_dict()
    srv.close()
    assert bad == []
    assert np.array_equal(post, sf2.predict_raw(Xq)[0])
    assert md["counters"]["hot_swaps"] == 1
    assert md["gauges"]["model_generation"] == 1
    # warm=True pre-compiled the new model's buckets: the digest changed
    assert md["gauges"]["active_model_digest"] != ""


def test_swap_pins_in_flight_requests(binary_booster):
    """A request admitted before the flip completes on the model it was
    validated against — even when the new model expects a DIFFERENT
    feature count, and even while the request still sits in the queue."""
    rng = np.random.RandomState(7)
    b_wide = _train_features(F + 3, seed=13)
    sf_old = binary_booster._forest(0, 12)
    sf_wide = b_wide._forest(0, 8)
    # a long coalescing window keeps the submitted request queued while
    # the swap lands, so execution deterministically happens post-flip
    srv = binary_booster.serve(max_batch_rows=64, batch_window_ms=300.0)
    Xq = _f32_data(rng, 16)
    fut = srv.submit(Xq)
    srv.swap_model(b_wide, warm=False, block=True)
    out = fut.result(30)
    assert np.array_equal(out, sf_old.predict_raw(Xq)[0])
    # post-swap traffic validates and serves against the new model
    Xw = _f32_data(rng, 10, f=F + 3)
    assert np.array_equal(srv.predict(Xw, timeout=30),
                          sf_wide.predict_raw(Xw)[0])
    with pytest.raises(ServingError):
        srv.submit(Xq)                    # old feature count now rejected
    srv.close()


def _train_features(f, rounds=8, seed=0):
    rng = np.random.RandomState(seed)
    X = _f32_data(rng, 1200, f)
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    return lgb.train({"objective": "binary", "verbosity": -1,
                      "num_leaves": 15}, lgb.Dataset(X, label=y),
                     num_boost_round=rounds, verbose_eval=False)


def test_submit_copies_input(binary_booster):
    """submit() must own its rows: a caller refilling a preallocated
    buffer while the request is still queued cannot corrupt results."""
    sf = binary_booster._forest(0, 12)
    rng = np.random.RandomState(31)
    srv = binary_booster.serve(max_batch_rows=64, batch_window_ms=100.0)
    buf = _f32_data(rng, 12)
    want = sf.predict_raw(buf)[0]
    fut = srv.submit(buf)
    buf[:] = 0.0                       # caller reuses the buffer
    out = fut.result(30)
    srv.close()
    assert np.array_equal(out, want)


def test_swap_across_num_class(binary_booster):
    """warm=True must pre-compile the seen buckets for the new model
    even when the swap changes num_class (binary -> multiclass)."""
    b3 = _train(num_class=3, rounds=4, seed=9)
    sf3 = b3._forest(0, 4)
    srv = binary_booster.serve(max_batch_rows=64)
    rng = np.random.RandomState(5)
    srv.predict(_f32_data(rng, 10))    # seed the warm set (bucket 16)
    srv.swap_model(b3, warm=True, block=True)
    compiles_after_warm = srv.metrics_dict()["counters"]["compile_events"]
    Xq = _f32_data(rng, 10)
    out = srv.predict(Xq)
    md = srv.metrics_dict()
    srv.close()
    assert np.array_equal(out, sf3.predict_raw(Xq, num_class=3).T)
    assert md["counters"]["compile_events"] == compiles_after_warm


def test_swap_nonblocking(binary_booster):
    b2 = _train(rounds=5, leaves=7, seed=6)
    sf2 = b2._forest(0, 5)
    srv = binary_booster.serve(max_batch_rows=64)
    rng = np.random.RandomState(2)
    srv.predict(_f32_data(rng, 10))           # seed the warm set
    t = srv.swap_model(b2, warm=True, block=False)
    assert t is not None
    t.join(30)
    Xq = _f32_data(rng, 10)
    out = srv.predict(Xq)
    srv.close()
    assert np.array_equal(out, sf2.predict_raw(Xq)[0])


# ------------------------------------------- deadline / backpressure / drain


def test_deadline_rejection(binary_booster):
    srv = binary_booster.serve(max_batch_rows=64, batch_window_ms=0.5)
    rng = np.random.RandomState(1)
    fut = srv.submit(_f32_data(rng, 8), deadline_ms=1e-4)
    with pytest.raises(DeadlineExceeded):
        fut.result(10)
    # a sane deadline still serves
    out = srv.submit(_f32_data(rng, 8), deadline_ms=30_000).result(30)
    assert out.shape == (8,)
    md = srv.metrics_dict()
    srv.close()
    assert md["counters"]["requests_rejected_deadline"] >= 1


def test_queue_backpressure(binary_booster):
    srv = binary_booster.serve(max_batch_rows=64, max_queue_rows=128,
                               batch_window_ms=200.0)
    rng = np.random.RandomState(1)
    X = _f32_data(rng, 64)
    accepted = []
    with pytest.raises(QueueFull):
        for _ in range(64):                    # far beyond 128 queued rows
            accepted.append(srv.submit(X))
    assert srv.metrics_dict()["counters"]["requests_rejected_queue_full"] >= 1
    # accepted work still completes (reject-new, not drop-old)
    for fut in accepted:
        assert fut.result(30).shape == (64,)
    # a request that can NEVER fit is rejected with a non-retryable
    # ServingError, not a QueueFull that backoff cannot satisfy
    with pytest.raises(ServingError) as ei:
        srv.submit(_f32_data(rng, 129))
    assert not isinstance(ei.value, QueueFull)
    srv.close()


def test_close_semantics(binary_booster):
    rng = np.random.RandomState(8)
    srv = binary_booster.serve(max_batch_rows=64, batch_window_ms=100.0)
    futs = [srv.submit(_f32_data(rng, 16)) for _ in range(4)]
    srv.close(drain=True, timeout=30)          # graceful: all served
    for f in futs:
        assert f.result(0).shape == (16,)
    with pytest.raises(ServerClosed):
        srv.submit(_f32_data(rng, 4))
    # drain=False fails whatever is still queued
    srv2 = binary_booster.serve(max_batch_rows=64, batch_window_ms=500.0)
    futs2 = [srv2.submit(_f32_data(rng, 16)) for _ in range(8)]
    srv2.close(drain=False, timeout=30)
    outcomes = {"served": 0, "closed": 0}
    for f in futs2:
        try:
            f.result(5)
            outcomes["served"] += 1
        except ServerClosed:
            outcomes["closed"] += 1
    assert outcomes["closed"] >= 1             # tail of the queue was failed


def test_cancelled_future_does_not_wedge_scheduler(binary_booster):
    """Caller-side cancellation (asyncio.wait_for on apredict cancels the
    wrapped Future) must neither kill the singleton scheduler thread nor
    fail co-batched requests — the server keeps serving."""
    rng = np.random.RandomState(21)
    sf = binary_booster._forest(0, 12)
    srv = binary_booster.serve(max_batch_rows=64, batch_window_ms=100.0)
    for _ in range(3):
        fut = srv.submit(_f32_data(rng, 8))
        fut.cancel()
    Xq = _f32_data(rng, 12)
    out = srv.predict(Xq, timeout=30)     # scheduler thread still alive
    srv.close()
    assert np.array_equal(out, sf.predict_raw(Xq)[0])


def test_async_predict(binary_booster):
    import asyncio
    sf = binary_booster._forest(0, 12)
    rng = np.random.RandomState(12)
    Xq = _f32_data(rng, 25)

    async def go(srv):
        outs = await asyncio.gather(*[srv.apredict(Xq) for _ in range(4)])
        return outs

    with binary_booster.serve(max_batch_rows=128) as srv:
        outs = asyncio.run(go(srv))
    ref = sf.predict_raw(Xq)[0]
    for out in outs:
        assert np.array_equal(out, ref)


# ------------------------------------------------------------ stress (slow)


@pytest.mark.slow
def test_serving_stress(binary_booster):
    """1k mixed-shape requests from 8 threads; registered slow so tier-1
    stays fast (tools/serve_smoke.py is the CLI twin)."""
    sf = binary_booster._forest(0, 12)
    srv = binary_booster.serve(max_batch_rows=512, batch_window_ms=2.0)
    bad = []

    def worker(seed):
        r = np.random.RandomState(seed)
        for _ in range(125):
            Xr = _f32_data(r, int(r.randint(1, 700)))
            out = srv.predict(Xr, timeout=60)
            if not np.array_equal(out, sf.predict_raw(Xr)[0]):
                bad.append(seed)

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    md = srv.metrics_dict()
    srv.close()
    assert bad == []
    assert md["counters"]["requests_completed"] == 1000
    assert md["counters"]["multi_submitter_batches"] >= 1


# --------------------------------------- take_from_table on-device probe


def test_table_matmul_probe_fallback(monkeypatch):
    """A backend failing the one-time exactness probe must demote
    take_from_table to the plain gather (ADVICE.md round 5)."""
    import jax.numpy as jnp
    import lightgbm_tpu.ops.histogram as H

    monkeypatch.setattr(H, "on_accelerator", lambda: True)
    table = jnp.asarray(np.linspace(-2, 2, 9).astype(np.float32))
    idx = jnp.asarray(np.arange(9, dtype=np.int32))

    # healthy backend: probe passes once, matmul path serves
    monkeypatch.setattr(H, "_TABLE_MATMUL_PROBE", {})
    out = np.asarray(H.take_from_table(table, idx))
    np.testing.assert_array_equal(out, np.asarray(table))
    assert H._TABLE_MATMUL_PROBE == {"cpu": True}

    # broken backend: matmul path perturbs values -> probe must demote
    monkeypatch.setattr(H, "_TABLE_MATMUL_PROBE", {})
    real = H._take_matmul

    def skewed(t, i, leading=False, block=65536):
        return real(t, i, leading, block) * 1.0000001

    monkeypatch.setattr(H, "_take_matmul", skewed)
    with pytest.warns(UserWarning, match="NOT bit-exact"):
        out = np.asarray(H.take_from_table(table, idx))
    np.testing.assert_array_equal(out, np.asarray(table))  # gather served
    assert H._TABLE_MATMUL_PROBE == {"cpu": False}
    # verdict is cached: no re-probe, still the gather
    out = np.asarray(H.take_from_table(table, idx))
    np.testing.assert_array_equal(out, np.asarray(table))


# ------------------------------------------------- swap probe / quarantine


def test_swap_probe_quarantines_poisoned_model(binary_booster):
    """A hot-swap candidate producing non-finite output must be rejected
    BEFORE promotion: SwapQuarantined raised, generation unchanged,
    swap_quarantines counted, old model still serving identical bytes."""
    from lightgbm_tpu.serving import SwapQuarantined
    rng = np.random.RandomState(5)
    X = _f32_data(rng, 32)
    srv = binary_booster.serve(backend="host")
    try:
        before = srv.predict(X)
        gen = srv.metrics.gauge("model_generation").value
        poisoned = _train(rounds=4, seed=9)
        poisoned.boosting.models[0].leaf_value[:] = np.nan
        with pytest.raises(SwapQuarantined):
            srv.swap_model(poisoned)
        assert srv.metrics.gauge("model_generation").value == gen
        assert srv.metrics.counter("swap_quarantines").value == 1
        assert srv.metrics.counter("swap_failures").value >= 1
        np.testing.assert_array_equal(srv.predict(X), before)
    finally:
        srv.close()


def test_swap_probe_quarantines_raising_model(binary_booster):
    """A candidate whose predict path RAISES is quarantined the same way
    (probe catches the exception, not the first live batch)."""
    from lightgbm_tpu.serving import SwapQuarantined
    srv = binary_booster.serve(backend="host")
    try:
        bad = _train(rounds=4, seed=11)

        class _Exploding:
            num_trees = 0

            def predict_raw(self, Xpad, num_class=1):
                raise RuntimeError("boom")

        gen = srv.metrics.gauge("model_generation").value
        # sabotage the CompiledModel the registry will build: swap via the
        # registry directly with a broken forest
        from lightgbm_tpu.serving.registry import CompiledModel
        new = CompiledModel(bad, backend="host")
        new.forest = _Exploding()
        new.make_program(8)  # sanity: building the callable is fine
        with pytest.raises(SwapQuarantined):
            srv.models._probe(new)
        assert srv.metrics.counter("swap_quarantines").value == 1
        assert srv.metrics.gauge("model_generation").value == gen
    finally:
        srv.close()


def test_swap_healthy_model_passes_probe(binary_booster):
    srv = binary_booster.serve(backend="host")
    try:
        gen = srv.metrics.gauge("model_generation").value
        srv.swap_model(_train(rounds=6, seed=13))
        assert srv.metrics.gauge("model_generation").value == gen + 1
        assert srv.metrics.counter("swap_quarantines").value == 0
    finally:
        srv.close()
